"""Topology-aware collectives (ISSUE: torus-native multi-phase RS+AG and
the Swing schedule): torus detection/override plumbing, topology-aware
``auto`` resolution and degradation, per-phase wire-byte accounting, the
acceptance parity matrix for ``rs_ag_2d``/``chunked_rs_ag_2d``/``swing``
vs ``psum`` on a simulated 2x4 torus, doctor's topology finding, the
trace-merge algorithm summary, and the 4-process 2x2 smoke."""

import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import overlap
from horovod_tpu.parallel import mesh as hmesh


TALGS = ("rs_ag_2d", "chunked_rs_ag_2d", "swing")


class _FakeDev:
    """Stand-in for a TPU device: .coords + .core_on_chip."""

    def __init__(self, coords, core=0):
        self.coords = coords
        self.core_on_chip = core


class TestTopologyDetection:
    def test_parse_topology_grammar(self):
        assert hmesh.parse_topology("2x2") == (2, 2)
        assert hmesh.parse_topology("4X8") == (4, 8)
        assert hmesh.parse_topology("16") == (16,)
        for bad in ("", "2xbanana", "0x4", "-2x4", "x", "2x"):
            with pytest.raises(ValueError, match="HOROVOD_TOPOLOGY"):
                hmesh.parse_topology(bad)

    def test_override_validates_product(self):
        assert hmesh.detect_topology(8, override="2x4") == (2, 4)
        with pytest.raises(ValueError, match="8"):
            hmesh.detect_topology(8, override="3x3")

    def test_cpu_falls_back_to_ring(self):
        # CPU devices have no .coords: the world is a 1-D ring.
        assert hmesh.detect_topology(len(jax.devices()), jax.devices()) \
            == (len(jax.devices()),)
        assert hmesh.detect_topology(1) == (1,)

    def test_tpu_coords_spans(self):
        # 2x2 chip grid, single core per chip: extent-1 dims dropped.
        devs = [_FakeDev((x, y, 0)) for x in range(2) for y in range(2)]
        assert hmesh.detect_topology(4, devs) == (2, 2)
        # 2 chips x 2 cores: core_on_chip becomes the trailing dim.
        devs = [_FakeDev((x, 0, 0), core=c) for x in range(2)
                for c in range(2)]
        assert hmesh.detect_topology(4, devs) == (2, 2)
        # span product that cannot explain the world -> ring fallback
        devs = [_FakeDev((x, 0, 0)) for x in range(2)] * 3
        assert hmesh.detect_topology(6, devs) == (6,)

    def test_torus_groups(self):
        g = hmesh.torus_groups((2, 4))
        # dim 0: columns of the row-major 2x4 grid; dim 1: the rows
        assert g[0] == [[0, 4], [1, 5], [2, 6], [3, 7]]
        assert g[1] == [[0, 1, 2, 3], [4, 5, 6, 7]]
        # every dim's groups partition the world
        for groups in g:
            flat = sorted(r for grp in groups for r in grp)
            assert flat == list(range(8))


class TestResolveTopologyAware:
    def r(self, *a, **kw):
        return overlap.resolve_algorithm(*a, **kw)

    def test_auto_picks_2d_on_torus(self):
        topo = (2, 4)
        assert self.r("auto", overlap.CHUNKED_MIN_BYTES, hvd.Sum, 8,
                      True, topology=topo) == "chunked_rs_ag_2d"
        assert self.r("auto", overlap.RS_AG_MIN_BYTES, hvd.Sum, 8,
                      True, topology=topo) == "rs_ag_2d"
        # wire default composes onto the 2D picks like the 1-D ones
        assert self.r("auto", overlap.CHUNKED_MIN_BYTES, hvd.Sum, 8,
                      True, wire="int8", topology=topo) \
            == "chunked_rs_ag_2d_int8"
        # latency-bound buckets keep the exact fused psum
        assert self.r("auto", 1024, hvd.Sum, 8, True,
                      topology=topo) == "psum"

    def test_auto_keeps_1d_on_ring(self):
        for topo in (None, (8,), (8, 1)):
            assert self.r("auto", overlap.CHUNKED_MIN_BYTES, hvd.Sum, 8,
                          True, topology=topo) == "chunked_rs_ag"

    def test_explicit_2d_degrades_to_1d_base(self):
        # a pinned *_2d on a 1-D ring runs the 1-D base, same wire
        assert self.r("rs_ag_2d", 1 << 20, hvd.Sum, 8, True,
                      topology=(8,)) == "rs_ag"
        assert self.r("chunked_rs_ag_2d_int8", 1 << 20, hvd.Sum, 8,
                      True, topology=None) == "chunked_rs_ag_int8"
        # with a real torus the explicit request sticks
        assert self.r("rs_ag_2d", 1 << 20, hvd.Sum, 8, True,
                      topology=(2, 4)) == "rs_ag_2d"

    def test_swing_needs_power_of_two_world(self):
        assert self.r("swing", 1 << 20, hvd.Sum, 6, True) == "psum"
        assert self.r("swing", 1 << 20, hvd.Sum, 8, True) == "swing"

    def test_unknown_rejected(self):
        with pytest.raises(ValueError, match="butterfly"):
            self.r("butterfly", 1024, hvd.Sum, 8, True)


class TestWireBytesByPhase:
    def test_psum_single_leg(self):
        assert overlap.wire_bytes_by_phase("psum", 1000, "fp32", 8) \
            == {"all": 4000}

    def test_rs_ag_two_legs(self):
        got = overlap.wire_bytes_by_phase("rs_ag", 1000, "fp32", 8)
        assert got == {"rs": 4000, "ag": 4000}

    def test_2d_phases_shrink_by_dim_extent(self):
        got = overlap.wire_bytes_by_phase("rs_ag_2d", 1000, "fp32", 8,
                                          dims=(2, 4))
        # RS d0 sees the full bucket; RS d1 the 1/2 shard; AG mirrors.
        assert got == {"rs_d0": 4000, "rs_d1": 2000,
                       "ag_d1": 2000, "ag_d0": 4000}
        # degraded (no usable torus): one RS + one AG over the full ring
        got = overlap.wire_bytes_by_phase("rs_ag_2d", 1000, "fp32", 8,
                                          dims=None)
        assert got == {"rs_d0": 4000, "ag_d0": 4000}

    def test_swing_geometric_series(self):
        got = overlap.wire_bytes_by_phase("swing", 1024, "fp32", 8)
        # sum over steps of m/2^(s+1) = c*(n-1) elements per direction
        assert got == {"rs": 4 * 128 * 7, "ag": 4 * 128 * 7}

    def test_quantized_scales_ride_every_leg(self):
        from horovod_tpu.ops.quantized import BLOCK
        m = 8 * BLOCK
        got = overlap.wire_bytes_by_phase("rs_ag_2d", m, "int8", 8,
                                          dims=(2, 4))
        for ph, b in got.items():
            assert b > 0 and b < 4 * m      # compressed on every leg
        assert got["rs_d0"] == m + 4 * (m // BLOCK)


@pytest.fixture(scope="class")
def torus_2x4():
    """Re-init the 8-device world as a simulated 2x4 torus."""
    os.environ["HOROVOD_TOPOLOGY"] = "2x4"
    try:
        hvd.init()
        assert hvd.topology() == (2, 4)
        yield
    finally:
        del os.environ["HOROVOD_TOPOLOGY"]
        hvd.init()


def _qtol(alg, x, k):
    steps = 127 if "int8" in alg else 8
    return 3.0 * k * float(np.abs(np.asarray(x, np.float32)).max()) / steps


@pytest.mark.usefixtures("torus_2x4")
class TestTopologyParityMatrix:
    """Acceptance matrix: the topology-aware schedules agree with
    ``psum`` across Sum/Average x fp32/bf16 x subset process sets x
    eager/traced x wire=fp32/int8 on the simulated 2x4 torus, and every
    row (rank) of the eager result is bit-identical."""

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    @pytest.mark.parametrize("op", [hvd.Sum, hvd.Average])
    @pytest.mark.parametrize("alg", TALGS)
    def test_matrix_eager(self, rng, dtype, op, alg):
        n = hvd.size()
        x = jnp.asarray(rng.standard_normal((n, 1001)), dtype)
        base = np.asarray(hvd.allreduce(x, op=op, algorithm="psum")
                          ).astype(np.float64)
        got_j = hvd.allreduce(x, op=op, algorithm=alg, overlap_chunks=3)
        assert got_j.dtype == x.dtype
        got = np.asarray(got_j)
        # cross-rank agreement: every row holds the same bytes
        for r in range(1, n):
            np.testing.assert_array_equal(got[r], got[0])
        got = got.astype(np.float64)
        if dtype == jnp.bfloat16:
            # within ~1 bf16 ulp of the psum result (different but
            # equally-valid reduction orders at 8-bit mantissa)
            bound = float(np.abs(base).max()) * 2.0 ** -7 + 1e-6
        else:
            bound = 1e-5 + 2e-6 * float(np.abs(base).max())
        assert np.abs(got - base).max() <= bound, \
            f"{alg} vs psum, op={op} dtype={dtype}"

    @pytest.mark.parametrize("alg", ["rs_ag_2d_int8",
                                     "chunked_rs_ag_2d_int8",
                                     "rs_ag_2d_fp8"])
    def test_matrix_quantized_wire(self, rng, alg):
        n = hvd.size()
        x = jnp.asarray(rng.standard_normal((n, 901)), jnp.float32)
        base = np.asarray(hvd.allreduce(x, op=hvd.Average,
                                        algorithm="psum"))
        got = np.asarray(hvd.allreduce(x, op=hvd.Average, algorithm=alg,
                                       overlap_chunks=2))
        for r in range(1, n):
            np.testing.assert_array_equal(got[r], got[0])
        assert np.abs(got - base).max() < _qtol(alg, x, 1)

    @pytest.mark.parametrize("op", [hvd.Sum, hvd.Average])
    @pytest.mark.parametrize("alg", TALGS + ("chunked_rs_ag_2d_int8",))
    def test_subset_process_set(self, rng, alg, op):
        n = hvd.size()
        members = [1, 3, 6]
        ps = hvd.add_process_set(members)
        try:
            x = rng.standard_normal((n, 515)).astype(np.float32)
            got = np.asarray(hvd.allreduce(
                jnp.asarray(x), op=op, process_set=ps, algorithm=alg,
                overlap_chunks=2))
            want = (x[members].sum(0) if op == hvd.Sum
                    else x[members].mean(0))
            k = len(members) if op == hvd.Sum else 1
            tol = (_qtol(alg, x, k) if "int8" in alg
                   else 1e-4 * max(1.0, k))
            for m in members:
                assert np.abs(got[m] - want).max() < tol, (alg, op)
            for m in members[1:]:
                np.testing.assert_array_equal(got[m], got[members[0]])
            # non-members get their input back exactly
            np.testing.assert_array_equal(got[0], x[0])
        finally:
            hvd.remove_process_set(ps)

    @pytest.mark.parametrize("alg", TALGS)
    def test_traced_lowering_matches(self, rng, alg):
        n = hvd.size()
        x = rng.standard_normal((n, 1029)).astype(np.float32)
        fn = hvd.spmd(lambda v: hvd.allreduce(v, op=hvd.Average,
                                              algorithm=alg,
                                              overlap_chunks=3),
                      in_specs=P("hvd"), out_specs=P("hvd"))
        ref = hvd.spmd(lambda v: hvd.allreduce(v, op=hvd.Average,
                                               algorithm="psum"),
                       in_specs=P("hvd"), out_specs=P("hvd"))
        got = np.asarray(fn(jnp.asarray(x)))
        base = np.asarray(ref(jnp.asarray(x)))
        np.testing.assert_allclose(got, base, rtol=2e-6, atol=1e-5)

    def test_auto_selects_2d_on_detected_torus(self):
        # Acceptance: auto resolves >=32MB buckets to the 2D lowering
        # once the torus is detected (feeding core.topology() through).
        topo = hvd.topology()
        assert topo == (2, 4)
        assert overlap.resolve_algorithm(
            "auto", 32 * 1024 * 1024, hvd.Sum, hvd.size(), True,
            topology=topo) == "chunked_rs_ag_2d"
        assert overlap.resolve_algorithm(
            "auto", 4 * 1024 * 1024, hvd.Sum, hvd.size(), True,
            topology=topo) == "rs_ag_2d"

    def test_metrics_observability(self, rng):
        """allreduce_algorithm_total{algorithm="rs_ag_2d"} plus all four
        per-phase wire-byte legs show up in hvd.metrics()."""
        hvd.reset_metrics()
        n = hvd.size()
        x = jnp.asarray(rng.standard_normal((n, 2003)), jnp.float32)
        hvd.allreduce(x, op=hvd.Sum, algorithm="rs_ag_2d",
                      name="topo_metrics_probe")
        snap = hvd.metrics()
        algs = {c["labels"]["algorithm"]: c["value"]
                for c in snap["counters"]["allreduce_algorithm_total"]}
        assert algs.get("rs_ag_2d", 0) >= 1, algs
        legs = {c["labels"]["phase"]: c["value"]
                for c in snap["counters"]["allreduce_wire_bytes_total"]
                if c["labels"]["algorithm"] == "rs_ag_2d"}
        assert set(legs) == {"rs_d0", "rs_d1", "ag_d1", "ag_d0"}
        assert legs["rs_d0"] == 4 * 2003            # full bucket, dim 0
        assert legs["rs_d1"] == 4 * -(-2003 // 2)   # 1/2 shard, dim 1
        assert legs["ag_d0"] == legs["rs_d0"]

    def test_build_info_and_gauges(self):
        assert hvd.build_info()["topology"] == "2x4"
        assert hvd.topology() == (2, 4)
        snap = hvd.metrics()
        if "config_topology" not in snap.get("gauges", {}):
            hvd.init()      # an earlier reset_metrics wiped the stamp
            snap = hvd.metrics()
        dims = {g["labels"]["dim"]: g["value"]
                for g in snap["gauges"]["config_topology"]}
        assert dims["0"] == 2 and dims["1"] == 4
        # unused trailing slots are zeroed, not absent (offline parity)
        assert dims["2"] == 0 and dims["3"] == 0


class TestTopologyConfig:
    def test_invalid_spec_rejected_at_refresh(self, monkeypatch):
        from horovod_tpu import config as hconfig
        monkeypatch.setenv("HOROVOD_TOPOLOGY", "2xbanana")
        with pytest.raises(ValueError, match="HOROVOD_TOPOLOGY"):
            hconfig.refresh()
        monkeypatch.delenv("HOROVOD_TOPOLOGY")
        hconfig.refresh()

    def test_product_mismatch_rejected_at_init(self, monkeypatch):
        from horovod_tpu import config as hconfig
        monkeypatch.setenv("HOROVOD_TOPOLOGY", "3x3")
        try:
            with pytest.raises(ValueError, match="3x3"):
                hvd.init()
        finally:
            monkeypatch.delenv("HOROVOD_TOPOLOGY")
            hconfig.refresh()
            hvd.init()

    def test_build_info_before_init_shows_override(self, monkeypatch):
        from horovod_tpu import config as hconfig
        monkeypatch.setenv("HOROVOD_TOPOLOGY", "2x4")
        hconfig.refresh()
        try:
            assert hconfig.get_config().topology == "2x4"
        finally:
            monkeypatch.delenv("HOROVOD_TOPOLOGY")
            hconfig.refresh()


def _ctr(value, **labels):
    return {"labels": labels, "value": value}


def _topo_gauges(*dims):
    vals = list(dims) + [0] * (4 - len(dims))
    return [{"labels": {"dim": str(i)}, "value": v}
            for i, v in enumerate(vals)]


class TestDoctorTopology:
    def _snap(self, gauges, counters):
        return {"counters": counters, "gauges": gauges,
                "histograms": {}, "pending_collectives": []}

    def test_ring_on_torus_suggests_2d(self):
        from horovod_tpu.profiler import doctor
        snap = self._snap(
            {"config_topology": _topo_gauges(2, 4)},
            {"allreduce_wire_bytes_total": [
                _ctr(24 * 1024 * 1024, algorithm="chunked_rs_ag",
                     wire="fp32", phase="rs"),
                _ctr(24 * 1024 * 1024, algorithm="chunked_rs_ag",
                     wire="fp32", phase="ag"),
            ]})
        rep = doctor(snapshot=snap, trace=None, programs={})
        f = [x for x in rep["findings"]
             if x["category"] == "topology_ring"]
        assert len(f) == 1
        assert "rs_ag_2d" in f[0]["suggestion"]
        assert "2x4" in f[0]["title"]

    def test_quiet_when_2d_already_active(self):
        from horovod_tpu.profiler import doctor
        snap = self._snap(
            {"config_topology": _topo_gauges(2, 4)},
            {"allreduce_wire_bytes_total": [
                _ctr(48 * 1024 * 1024, algorithm="rs_ag_2d",
                     wire="fp32", phase="rs_d0"),
            ]})
        rep = doctor(snapshot=snap, trace=None, programs={})
        assert not [x for x in rep["findings"]
                    if x["category"] == "topology_ring"]

    def test_quiet_on_1d_torus(self):
        from horovod_tpu.profiler import doctor
        snap = self._snap(
            {"config_topology": _topo_gauges(8)},
            {"allreduce_wire_bytes_total": [
                _ctr(48 * 1024 * 1024, algorithm="chunked_rs_ag",
                     wire="fp32", phase="rs"),
            ]})
        rep = doctor(snapshot=snap, trace=None, programs={})
        assert not [x for x in rep["findings"]
                    if x["category"] == "topology_ring"]

    def test_quiet_below_threshold(self):
        from horovod_tpu.profiler import doctor
        snap = self._snap(
            {"config_topology": _topo_gauges(2, 4)},
            {"allreduce_wire_bytes_total": [
                _ctr(1024, algorithm="rs_ag", wire="fp32", phase="rs"),
            ]})
        rep = doctor(snapshot=snap, trace=None, programs={})
        assert not [x for x in rep["findings"]
                    if x["category"] == "topology_ring"]


class TestTraceMergeAlgorithms:
    def test_marker_summary(self):
        from horovod_tpu.trace_merge import overlap_report
        mk = {"name": "allreduce_algorithm", "ph": "i", "ts": 1.0,
              "args": {"algorithm": "rs_ag_2d", "wire": "fp32",
                       "wire_bytes": 120, "topology": "2x4",
                       "phases": {"rs_d0": 40, "rs_d1": 20,
                                  "ag_d1": 20, "ag_d0": 40}}}
        shards = [
            {"rank": 0, "events": [mk, dict(mk)]},
            # higher ranks carry the same trace-time markers; the summary
            # must read one representative shard, not multiply them
            {"rank": 1, "events": [mk]},
        ]
        rep = overlap_report(shards)
        alg = rep["algorithms"]["rs_ag_2d"]
        assert alg["buckets"] == 2
        assert alg["wire_bytes"] == 240
        assert alg["phase_bytes"] == {"rs_d0": 80, "rs_d1": 40,
                                      "ag_d1": 40, "ag_d0": 80}
        assert alg["topology"] == "2x4"
        assert alg["wire"] == "fp32"


class TestFourProcessTopoSmoke:
    def test_topo_smoke_four_process(self):
        """Acceptance drive: 4 real processes on a simulated 2x2 torus,
        bit-identical results across ranks for every topology-aware
        schedule (tools/topo_smoke.py, also `make topo-smoke`)."""
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        r = subprocess.run(
            [sys.executable, os.path.join(repo, "tools", "topo_smoke.py")],
            capture_output=True, text=True, timeout=600)
        assert r.returncode == 0, \
            f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
        assert "topo-smoke OK" in r.stdout
