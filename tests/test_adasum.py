"""Adasum generality: any world size (pre-pairing), process sets, and the
VHDD bandwidth path (upstream ``horovod/common/ops/adasum/adasum.h``;
VERDICT r1 item 9). The n=8 recursive-doubling parity test lives in
test_collectives.py; here we check the non-power-of-two structure, subsets,
and stability."""

import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd

N = 8


def combine(a, b):
    a = a.astype(np.float64)
    b = b.astype(np.float64)
    dot = np.vdot(a, b)
    asq = np.vdot(a, a)
    bsq = np.vdot(b, b)
    ca = 1.0 - dot / (2 * asq) if asq > 0 else 1.0
    cb = 1.0 - dot / (2 * bsq) if bsq > 0 else 1.0
    return ca * a + cb * b


def host_adasum(xs):
    """Reference mirroring the implementation's structure: pre-pair the
    k - p tail into the first ranks, XOR recursive doubling among the p
    actives, broadcast back (upstream's non-power-of-two handling)."""
    k = len(xs)
    if k == 1:
        return [xs[0].astype(np.float64)]
    p = 1 << (k.bit_length() - 1)
    r = k - p
    ys = [x.astype(np.float64) for x in xs[:p]]
    for i in range(r):
        ys[i] = combine(xs[i], xs[p + i])
    d = 1
    while d < p:
        ys = [combine(ys[i], ys[i ^ d]) for i in range(p)]
        d *= 2
    out = [None] * k
    for i in range(p):
        out[i] = ys[i]
    for i in range(r):
        out[p + i] = ys[i]
    return out


class TestAdasumGeneral:
    def test_n6_matches_reference(self, rng):
        """Non-power-of-two member count via a 6-rank process set."""
        x = rng.standard_normal((N, 33)).astype(np.float32)  # odd length
        ps = hvd.add_process_set(list(range(6)))
        try:
            out = np.asarray(hvd.allreduce(x, op=hvd.Adasum, process_set=ps))
        finally:
            hvd.remove_process_set(ps)
        ref = host_adasum([x[i] for i in range(6)])
        for i in range(6):
            np.testing.assert_allclose(out[i], ref[i], rtol=1e-4, atol=1e-5)
        # non-members get their input back
        for i in (6, 7):
            np.testing.assert_allclose(out[i], x[i], rtol=1e-6)

    def test_subset_k3(self, rng):
        x = rng.standard_normal((N, 16)).astype(np.float32)
        ps = hvd.add_process_set([1, 3, 6])
        try:
            out = np.asarray(hvd.allreduce(x, op=hvd.Adasum, process_set=ps))
        finally:
            hvd.remove_process_set(ps)
        ref = host_adasum([x[1], x[3], x[6]])
        for j, r_ in zip([1, 3, 6], ref):
            np.testing.assert_allclose(out[j], r_, rtol=1e-4, atol=1e-5)
        for j in (0, 2, 4, 5, 7):
            np.testing.assert_allclose(out[j], x[j], rtol=1e-6)

    @pytest.mark.parametrize("k", [2, 3, 5, 6, 7, 8])
    def test_any_world_size(self, rng, k):
        x = rng.standard_normal((N, 24)).astype(np.float32)
        ps = hvd.add_process_set(list(range(k))) if k < N else None
        try:
            out = np.asarray(hvd.allreduce(x, op=hvd.Adasum, process_set=ps))
        finally:
            if ps is not None:
                hvd.remove_process_set(ps)
        ref = host_adasum([x[i] for i in range(k)])
        for i in range(k):
            np.testing.assert_allclose(out[i], ref[i], rtol=1e-4, atol=1e-5)

    def test_stability_identical_inputs(self, rng):
        """adasum(v, v, ..., v) == v: the fixed point that makes large-batch
        training stable (upstream's motivating property)."""
        v = rng.standard_normal((13,)).astype(np.float32)
        x = np.broadcast_to(v, (N,) + v.shape).copy()
        ps = hvd.add_process_set(list(range(6)))
        try:
            out = np.asarray(hvd.allreduce(x, op=hvd.Adasum, process_set=ps))
        finally:
            hvd.remove_process_set(ps)
        for i in range(6):
            np.testing.assert_allclose(out[i], v, rtol=1e-4, atol=1e-5)

    def test_orthogonal_pair_sums(self, rng):
        """Orthogonal gradients add (dot = 0 -> plain sum), n=2."""
        a = np.zeros(8, np.float32); a[0] = 3.0
        b = np.zeros(8, np.float32); b[1] = 4.0
        x = np.zeros((N, 8), np.float32)
        x[0], x[1] = a, b
        ps = hvd.add_process_set([0, 1])
        try:
            out = np.asarray(hvd.allreduce(x, op=hvd.Adasum, process_set=ps))
        finally:
            hvd.remove_process_set(ps)
        np.testing.assert_allclose(out[0], a + b, rtol=1e-5, atol=1e-6)


class TestHierarchicalAdasum:
    """Local-group average then cross-group Adasum (upstream
    HOROVOD_HIERARCHICAL_ALLREDUCE + Adasum)."""

    def test_two_groups_matches_reference(self, rng):
        from jax.sharding import PartitionSpec as P
        from horovod_tpu.adasum import hierarchical_adasum_allreduce

        x = rng.standard_normal((N, 17)).astype(np.float32)
        groups = [[0, 1, 2, 3], [4, 5, 6, 7]]

        def body(xs):
            return hierarchical_adasum_allreduce(xs[0], "hvd", N, groups)[None]

        out = np.asarray(hvd.spmd(body, in_specs=P("hvd"),
                                  out_specs=P("hvd"))(jnp.asarray(x)))
        m0 = x[:4].astype(np.float64).mean(0)
        m1 = x[4:].astype(np.float64).mean(0)
        want = combine(m0, m1)
        for i in range(N):
            np.testing.assert_allclose(out[i], want, rtol=1e-4, atol=1e-5)

    def test_single_group_is_plain_average(self, rng):
        from jax.sharding import PartitionSpec as P
        from horovod_tpu.adasum import hierarchical_adasum_allreduce

        x = rng.standard_normal((N, 9)).astype(np.float32)

        def body(xs):
            return hierarchical_adasum_allreduce(
                xs[0], "hvd", N, [list(range(N))])[None]

        out = np.asarray(hvd.spmd(body, in_specs=P("hvd"),
                                  out_specs=P("hvd"))(jnp.asarray(x)))
        np.testing.assert_allclose(out[0], x.mean(0), rtol=1e-5, atol=1e-6)

    def test_env_flag_routes_allreduce(self, rng, monkeypatch):
        # Single process => one group of all devices => plain average.
        monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLREDUCE", "1")
        x = rng.standard_normal((N, 6)).astype(np.float32)
        out = np.asarray(hvd.allreduce(x, op=hvd.Adasum))
        np.testing.assert_allclose(out[0], x.mean(0), rtol=1e-5, atol=1e-6)

    def test_unequal_groups_match_reference(self, rng):
        """Unequal group sizes (the subset-process-set shape: per-host
        member counts differ) run the masked-ppermute local phases and
        match the host reference; VERDICT r3 item 7."""
        from jax.sharding import PartitionSpec as P
        from horovod_tpu.adasum import hierarchical_adasum_allreduce

        x = rng.standard_normal((N, 13)).astype(np.float32)
        groups = [[0, 1, 2], [3, 4, 5, 6, 7]]

        def body(xs):
            return hierarchical_adasum_allreduce(
                xs[0], "hvd", N, groups)[None]

        out = np.asarray(hvd.spmd(body, in_specs=P("hvd"),
                                  out_specs=P("hvd"))(jnp.asarray(x)))
        m0 = x[:3].astype(np.float64).mean(0)
        m1 = x[3:].astype(np.float64).mean(0)
        want = combine(m0, m1)
        for i in range(N):
            np.testing.assert_allclose(out[i], want, rtol=1e-4, atol=1e-5)

    def test_partial_axis_groups_nonmembers_passthrough(self, rng):
        """Groups that do NOT cover the axis (a subset process set):
        members get the hierarchical result, non-members x back."""
        from jax.sharding import PartitionSpec as P
        from horovod_tpu.adasum import hierarchical_adasum_allreduce

        x = rng.standard_normal((N, 11)).astype(np.float32)
        groups = [[0, 1, 2], [4, 5]]          # 3, 6, 7 are non-members

        def body(xs):
            return hierarchical_adasum_allreduce(
                xs[0], "hvd", N, groups)[None]

        out = np.asarray(hvd.spmd(body, in_specs=P("hvd"),
                                  out_specs=P("hvd"))(jnp.asarray(x)))
        want = combine(x[:3].astype(np.float64).mean(0),
                       x[4:6].astype(np.float64).mean(0))
        for i in (0, 1, 2, 4, 5):
            np.testing.assert_allclose(out[i], want, rtol=1e-4, atol=1e-5)
        for i in (3, 6, 7):
            np.testing.assert_array_equal(out[i], x[i])

    def test_env_flag_subset_process_set(self, rng, monkeypatch):
        """HOROVOD_HIERARCHICAL_ALLREDUCE + a subset process set (the two
        NotImplementedErrors of VERDICT r3 item 7): single test process =>
        one group of the member ranks => hierarchical degrades to the
        member mean; non-members get x back."""
        monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLREDUCE", "1")
        members = [1, 3, 5]
        x = rng.standard_normal((N, 6)).astype(np.float32)
        ps = hvd.add_process_set(members)
        try:
            out = np.asarray(hvd.allreduce(x, op=hvd.Adasum,
                                           process_set=ps))
        finally:
            hvd.remove_process_set(ps)
        want = x[members].mean(0)
        for m in members:
            np.testing.assert_allclose(out[m], want, rtol=1e-5, atol=1e-6)
        for nm in sorted(set(range(N)) - set(members)):
            np.testing.assert_array_equal(out[nm], x[nm])
