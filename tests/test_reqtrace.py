"""Request-scoped distributed tracing (ISSUE 15 tentpole): the span
buffer and wire context, ``request_report`` critical-path math, traced
in-process serving holding the ``decode_compiles == 1`` pin with offline
token parity, and the 3-process hedged smoke (``make reqtrace-smoke``).
"""

import json
import os
import sys
import time
from collections import deque

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from horovod_tpu import config as hconfig
from horovod_tpu.models.generate import generate
from horovod_tpu.serving import reqtrace
from horovod_tpu.serving.engine import InferenceEngine
from horovod_tpu.serving.replica import Dispatcher
from horovod_tpu.trace_merge import REQUEST_COMPONENTS, request_report

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def gpt2_setup():
    from horovod_tpu.models.gpt2 import GPT2, GPT2Config
    cfg = GPT2Config.tiny(dtype=jnp.float32)
    model = GPT2(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.ones((1, 4), jnp.int32))["params"]
    return model, params, cfg


@pytest.fixture
def tracing(monkeypatch):
    """Request tracing on, no shard dir (spans stay in the buffer)."""
    monkeypatch.setenv("HOROVOD_REQUEST_TRACE", "1")
    monkeypatch.delenv("HOROVOD_REQUEST_TRACE_DIR", raising=False)
    hconfig.refresh()
    reqtrace.reset()
    yield
    reqtrace.reset()
    monkeypatch.delenv("HOROVOD_REQUEST_TRACE", raising=False)
    hconfig.refresh()


# ---------------------------------------------------------------------------
# span buffer and wire context
# ---------------------------------------------------------------------------

class TestSpanBuffer:
    def test_off_by_default(self):
        assert reqtrace.enabled() is False

    def test_garbage_context_records_nothing(self, tracing):
        reqtrace.emit("SUBMIT", None, time.time(), 0.0)
        reqtrace.emit("SUBMIT", {"no": "tid"}, time.time(), 0.0)
        reqtrace.emit("SUBMIT", {"tid": "t", "sid": "NaN?"},
                      time.time(), 0.0)
        assert reqtrace.events() == []

    def test_wire_roundtrip_chains_parent(self, tracing):
        ctx = reqtrace.mint_context()
        w = ctx.wire()
        assert set(w) == {"tid", "sid"} and w["tid"] == ctx.tid
        # The wire dict is what rides the submit RPC params; spans
        # emitted against it chain to the minting hop's span id.
        reqtrace.emit("QUEUE", w, time.time(), 0.001, engine="e0")
        (ev,) = reqtrace.events()
        assert ev["cat"] == "request" and ev["ph"] == "X"
        assert ev["args"]["trace_id"] == ctx.tid
        assert ev["args"]["parent_id"] == ctx.sid
        assert ev["args"]["engine"] == "e0"
        assert ev["dur"] == pytest.approx(1000.0)      # seconds -> us

    def test_span_and_instant_shapes(self, tracing):
        ctx = reqtrace.mint_context()
        with reqtrace.span("PREFILL", ctx, chunk=0):
            time.sleep(0.002)
        reqtrace.instant("HEDGE", ctx, target="e1")
        prefill, hedge = reqtrace.events()
        assert prefill["name"] == "PREFILL" and prefill["ph"] == "X"
        assert prefill["dur"] >= 1000.0
        assert hedge["ph"] == "i" and hedge["s"] == "g"
        assert hedge["args"]["target"] == "e1"
        # ts is microseconds since this process's trace origin (minted
        # at the FIRST record — which is this span's exit, so its own
        # ts backs up by its duration)
        assert prefill["ts"] == pytest.approx(-prefill["dur"], rel=0.5)
        assert hedge["ts"] >= prefill["ts"]

    def test_buffer_bounded_drops_oldest(self, tracing, monkeypatch):
        monkeypatch.setattr(reqtrace, "_BUF", deque(maxlen=4))
        ctx = reqtrace.mint_context()
        for i in range(6):
            reqtrace.emit("DECODE", ctx, time.time(), 0.0, step=i)
        evs = reqtrace.events()
        assert len(evs) == 4
        assert [e["args"]["step"] for e in evs] == [2, 3, 4, 5]

    def test_flush_shard_format(self, tracing, tmp_path, monkeypatch):
        monkeypatch.setenv("HOROVOD_REQTRACE_LABEL", "unit")
        ctx = reqtrace.mint_context()
        reqtrace.emit("SUBMIT", ctx, time.time(), 0.0, request="r-1")
        path = reqtrace.flush(str(tmp_path / "shard.json"))
        doc = json.load(open(path))
        evs = doc["traceEvents"]
        assert evs[0]["ph"] == "M"
        assert evs[0]["args"]["name"] == "request unit"
        meta = evs[1]
        assert meta["name"] == "shard_meta"
        assert meta["args"]["role"] == "request"
        assert meta["args"]["proc"] == "unit"
        assert meta["args"]["wall0"] > 0 and meta["args"]["dropped"] == 0
        assert evs[2]["name"] == "SUBMIT"

    def test_flush_empty_buffer_returns_none(self, tracing, tmp_path):
        assert reqtrace.flush(str(tmp_path / "never.json")) is None
        assert not (tmp_path / "never.json").exists()


# ---------------------------------------------------------------------------
# request_report critical-path math (synthetic spans, no jax)
# ---------------------------------------------------------------------------

def _ev(name, tid, ts, dur=0.0, **args):
    a = {"trace_id": tid, "span_id": 1, "parent_id": 0}
    a.update(args)
    return {"name": name, "cat": "request", "ph": "X", "ts": ts,
            "dur": dur, "pid": 1, "tid": 0, "args": a}


class TestRequestReportMath:
    def test_hedged_breakdown_and_blame(self):
        evs = [
            _ev("SUBMIT", "t1", 0.0, request="r1"),
            _ev("ATTEMPT", "t1", 1_000.0, target="e0"),
            _ev("HEDGE", "t1", 50_000.0),
            _ev("ATTEMPT", "t1", 100_000.0, target="e1"),
            # loser e0's partial work must NOT be charged to this TTFT
            _ev("QUEUE", "t1", 2_000.0, dur=50_000.0, engine="e0"),
            _ev("QUEUE", "t1", 110_000.0, dur=5_000.0, engine="e1"),
            _ev("PREFILL", "t1", 120_000.0, dur=20_000.0, engine="e1"),
            _ev("DECODE", "t1", 140_000.0, dur=8_000.0, engine="e1"),
            _ev("HEDGE_WIN", "t1", 150_000.0, winner="e1"),
            _ev("FIRST_TOKEN", "t1", 150_000.0, engine="e1",
                ttft_s=0.16, request="r1"),
            # decode work after the first token is TPOT, not TTFT
            _ev("DECODE", "t1", 200_000.0, dur=8_000.0, engine="e1"),
            _ev("PUSH_DELIVERY", "t1", 155_000.0, dur=2_000.0),
            _ev("CLIENT_FIRST_TOKEN", "t1", 160_000.0, ttft_s=0.16),
        ]
        rep = request_report(evs)
        assert rep["count"] == 1 and rep["hedged"] == 1
        (rec,) = rep["requests"]
        assert rec["request"] == "r1"
        assert rec["hedged"] is True and rec["winner"] == "e1"
        assert rec["engine"] == "e1"
        bd = rec["breakdown_s"]
        # hedge_wait: SUBMIT until the WINNING attempt (ts 100000), not
        # the first one.
        assert bd["hedge_wait"] == pytest.approx(0.1)
        assert bd["queue"] == pytest.approx(0.005)       # e1's only
        assert bd["prefill"] == pytest.approx(0.02)
        assert bd["decode"] == pytest.approx(0.008)      # pre-first-token
        assert bd["push"] == pytest.approx(0.002)
        assert bd["other"] == pytest.approx(0.16 - 0.135)
        assert rec["breakdown_sum_s"] == pytest.approx(0.16)
        assert rec["ttft_s"] == pytest.approx(0.16)
        # blame: the hedge wait goes to the replica that was slow to
        # accept (first attempt's target), serving time to the winner.
        assert rep["replica_blame_s"]["e0"] == pytest.approx(0.1)
        assert rep["replica_blame_s"]["e1"] == pytest.approx(0.035)
        assert rep["dominant_replica"] == "e0"
        assert rep["dominant_component"] == "hedge_wait"
        assert rep["ttft_p50_s"] == pytest.approx(0.16)
        assert rep["p99_request"]["trace_id"] == "t1"

    def test_unhedged_fallback_ttft_from_server(self):
        evs = [
            _ev("SUBMIT", "t2", 0.0, request="r2"),
            _ev("QUEUE", "t2", 100.0, dur=1_000.0, engine="e0"),
            _ev("FIRST_TOKEN", "t2", 5_000.0, engine="e0", ttft_s=0.005),
        ]
        rep = request_report(evs)
        (rec,) = rep["requests"]
        assert rec["hedged"] is False and rec["winner"] is None
        assert rec["ttft_s"] == pytest.approx(0.005)     # server-side
        assert rec["breakdown_s"]["hedge_wait"] == 0.0
        assert rec["breakdown_s"]["queue"] == pytest.approx(0.001)
        assert set(rec["breakdown_s"]) == set(REQUEST_COMPONENTS)

    def test_empty_input(self):
        rep = request_report([])
        assert rep["count"] == 0 and rep["requests"] == []
        assert rep["dominant_component"] is None
        assert rep["dominant_replica"] is None


# ---------------------------------------------------------------------------
# traced serving: compile pin + parity + span coverage
# ---------------------------------------------------------------------------

class TestTracedServing:
    def test_tracing_off_emits_nothing(self, gpt2_setup):
        model, params, cfg = gpt2_setup
        reqtrace.reset()
        eng = InferenceEngine(model, params, slots=1, max_len=16,
                              block_size=4, prefill_chunk=1, name="off0")
        disp = Dispatcher([eng])
        req = disp.submit([1, 2, 3], 3)
        eng.run_until_idle()
        assert req.result(1)
        assert reqtrace.events() == []

    def test_traced_parity_single_decode_compile(self, gpt2_setup,
                                                 tracing, rng):
        """Acceptance pin: tracing ON does not perturb the jit story —
        decode compiles exactly once, outputs stay token-identical to
        offline generate() — while every request's spans land in the
        buffer with the engine attributed."""
        model, params, cfg = gpt2_setup
        eng = InferenceEngine(model, params, slots=3, max_len=32,
                              block_size=4, prefill_chunk=4, name="tr0")
        disp = Dispatcher([eng])
        lengths = [(6, 5), (3, 8), (9, 4)]
        prompts = [list(rng.integers(1, cfg.vocab_size, p))
                   for p, _ in lengths]
        reqs = [disp.submit(p, n) for p, (_, n) in zip(prompts, lengths)]
        eng.run_until_idle()

        for p, (plen, n), req in zip(prompts, lengths, reqs):
            want = np.asarray(generate(
                model, params, jnp.asarray([p], jnp.int32), n))[0, plen:]
            assert req.result(1) == list(want), req.id
        assert eng.decode_compiles == 1, \
            f"tracing perturbed the decode jit: {eng.decode_compiles}"

        evs = reqtrace.events()
        by_name = {}
        for e in evs:
            by_name.setdefault(e["name"], []).append(e)
        assert len(by_name["SUBMIT"]) == 3
        assert {e["args"]["request"] for e in by_name["SUBMIT"]} == \
            {r.id for r in reqs}
        for name in ("QUEUE", "PREFILL", "FIRST_TOKEN"):
            assert len(by_name.get(name, [])) >= 3, name
        assert all(e["args"]["engine"] == "tr0"
                   for e in by_name["FIRST_TOKEN"])

        rep = request_report(evs)
        assert rep["count"] == 3
        for rec in rep["requests"]:
            assert rec["engine"] == "tr0"
            assert rec["ttft_s"] is not None and rec["ttft_s"] > 0
            assert all(v >= 0.0 for v in rec["breakdown_s"].values())
            # components must account for TTFT (loose bound: host-side
            # wall clocks on shared CI hardware)
            assert rec["breakdown_sum_s"] <= rec["ttft_s"] * 1.5 + 0.05


# ---------------------------------------------------------------------------
# three-process hedged smoke (make reqtrace-smoke)
# ---------------------------------------------------------------------------

class TestReqtraceSmoke:
    def test_hedged_request_traced_end_to_end(self, tmp_path):
        sys.path.insert(0, os.path.join(_REPO, "tools"))
        try:
            import reqtrace_smoke
        finally:
            sys.path.remove(os.path.join(_REPO, "tools"))
        # run_smoke returns (rc, failure_text) — the text feeds the
        # rendezvous-flake retry in tools/smoke_util.py.
        rc, text = reqtrace_smoke.run_smoke(str(tmp_path))
        assert rc == 0, text
