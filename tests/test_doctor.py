"""hvd.doctor() automated diagnosis: golden-report over a canned
metrics+trace fixture, the offline CLI, and the 2-process doctor smoke."""

import json
import os
import subprocess
import sys

import pytest

import horovod_tpu as hvd
from horovod_tpu import profiler
from horovod_tpu.profiler import doctor, format_report, registry

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean():
    registry.reset()
    hvd.reset_metrics()
    yield
    registry.reset()
    hvd.reset_metrics()


def _ctr(name, value, **labels):
    return {"labels": labels, "value": value}


# ---------------------------------------------------------------------------
# canned fixture: a run with a manufactured straggler AND a recompile
# (plus background noise: healthy fusion, no stalls) — the golden input
# the satellite task asks for.
# ---------------------------------------------------------------------------

def _fixture_snapshot():
    return {
        "counters": {
            "recompiles_total": [
                _ctr("recompiles_total", 3, program="train_step"),
            ],
            "recompile_blame_total": [
                _ctr("recompile_blame_total", 3, program="train_step",
                     argument="seq_len"),
            ],
            "collective_calls_total": [
                _ctr("collective_calls_total", 40, kind="allreduce"),
            ],
        },
        "gauges": {},
        "histograms": {
            # healthy fill: must NOT produce a fusion finding
            "fusion_fill_ratio": [
                {"labels": {}, "count": 10, "sum": 8.0, "buckets": []},
            ],
        },
        "pending_collectives": [],
    }


def _fixture_trace_report():
    # rank 1 charged 250ms of peer wait across 3 correlated collectives
    return {
        "collectives": [{"op_id": i} for i in range(3)],
        "blame_seconds_by_rank": {"0": 0.004, "1": 0.25},
        "critical_path_seconds": 0.31,
    }


def _fixture_programs():
    return {
        "train_step": {
            "name": "train_step", "kind": "step",
            "recompiles": 3, "expected_recompiles": False,
            "last_blame": ["seq_len"],
            "blame_detail": {"seq_len": ["128", "256"]},
        },
    }


class TestGoldenReport:
    def test_ranked_findings_over_canned_fixture(self):
        """Satellite acceptance: doctor over a canned metrics+trace
        fixture with a manufactured straggler and recompile ranks both,
        and the recompile finding names the blamed argument."""
        report = doctor(snapshot=_fixture_snapshot(),
                        trace=_fixture_trace_report(),
                        programs=_fixture_programs())
        findings = report["findings"]
        assert findings, "golden fixture produced no findings"
        # ranked: severities non-increasing, rank field sequential
        sev = [f["severity"] for f in findings]
        assert sev == sorted(sev, reverse=True)
        assert [f["rank"] for f in findings] == list(
            range(1, len(findings) + 1))
        cats = [f["category"] for f in findings]
        assert "straggler" in cats and "recompile" in cats
        # healthy subsystems stay silent
        assert "fusion_fill" not in cats and "stall" not in cats
        assert report["healthy"] is False

    def test_straggler_finding_blames_rank_1(self):
        report = doctor(snapshot=_fixture_snapshot(),
                        trace=_fixture_trace_report(), programs={})
        s = [f for f in report["findings"]
             if f["category"] == "straggler"][0]
        assert s["evidence"]["blamed_rank"] == 1
        assert s["evidence"]["blame_seconds"] == pytest.approx(0.25)
        assert "rank 1" in s["title"]

    def test_recompile_finding_names_blamed_argument(self):
        report = doctor(snapshot=_fixture_snapshot(), trace=None,
                        programs=_fixture_programs())
        r = [f for f in report["findings"]
             if f["category"] == "recompile"][0]
        assert r["evidence"]["program"] == "train_step"
        assert r["evidence"]["recompiles"] == 3
        assert "seq_len" in r["evidence"]["blamed_arguments"]
        assert "seq_len" in r["title"]
        # the old -> new signature detail surfaces in the report text
        assert "128" in r["detail"] and "256" in r["detail"]

    def test_expected_recompiles_not_flagged(self):
        progs = _fixture_programs()
        progs["train_step"]["expected_recompiles"] = True
        report = doctor(snapshot=_fixture_snapshot(), trace=None,
                        programs=progs)
        assert not [f for f in report["findings"]
                    if f["category"] == "recompile"]

    def test_expected_recompiles_skip_survives_offline_snapshot(self):
        # An OFFLINE doctor (perf_doctor over flusher files, no live
        # registry) must still skip by-design churn: the expected tag
        # rides expected_recompiles_total in the exported snapshot.
        snap = _fixture_snapshot()
        snap["counters"]["recompiles_total"].append(
            _ctr("recompiles_total", 4, program="autotuned_step"))
        snap["counters"]["expected_recompiles_total"] = [
            _ctr("expected_recompiles_total", 4, program="autotuned_step")]
        report = doctor(snapshot=snap, trace=None, programs={})
        flagged = [f["evidence"]["program"] for f in report["findings"]
                   if f["category"] == "recompile"]
        assert "train_step" in flagged            # real churn still flagged
        assert "autotuned_step" not in flagged    # by-design churn skipped

    def test_autotuned_note_trace_exports_expected_counter(self):
        # The live end of the same contract: expected=True note_trace
        # recompiles bump expected_recompiles_total in the registry.
        from horovod_tpu import metrics as _metrics
        profiler.note_trace("at_prog", {"threshold": "1"}, expected=True)
        profiler.note_trace("at_prog", {"threshold": "2"}, expected=True)
        snap = _metrics.snapshot()
        vals = {s["labels"].get("program"): s["value"]
                for s in snap["counters"].get(
                    "expected_recompiles_total", [])}
        assert vals.get("at_prog") == 1
        report = doctor(snapshot=snap, trace=None, programs={})
        assert not [f for f in report["findings"]
                    if f["category"] == "recompile"]

    def test_blame_falls_back_to_metrics_labels(self):
        # No registry record (e.g. another rank's snapshot): the blamed
        # argument still comes from recompile_blame_total labels.
        report = doctor(snapshot=_fixture_snapshot(), trace=None,
                        programs={})
        r = [f for f in report["findings"]
             if f["category"] == "recompile"][0]
        assert "seq_len" in r["evidence"]["blamed_arguments"]

    def test_healthy_run_is_healthy(self):
        report = doctor(snapshot={"counters": {}, "gauges": {},
                                  "histograms": {}},
                        trace=None, programs={})
        assert report["healthy"] is True
        assert report["findings"] == []
        assert "nothing looks sick" in format_report(report)

    def test_low_mfu_finding(self):
        progs = {"bench:gpt2": {
            "name": "bench:gpt2", "expected_mfu": 0.5,
            "last_step_seconds": 0.1,
            "utilization": {"mfu": 0.1, "hfu": 0.3},
        }}
        report = doctor(snapshot={"counters": {}, "gauges": {},
                                  "histograms": {}},
                        trace=None, programs=progs)
        m = [f for f in report["findings"] if f["category"] == "low_mfu"]
        assert m and m[0]["evidence"]["program"] == "bench:gpt2"

    def test_total_rejection_is_backpressure_finding(self):
        # An engine rejecting EVERYTHING has submitted == 0 — the worst
        # backpressure case must not read healthy.
        snap = {
            "counters": {
                "serve_requests_total": [
                    _ctr("serve_requests_total", 50, status="rejected"),
                ],
            },
            "gauges": {}, "histograms": {},
        }
        report = doctor(snapshot=snap, trace=None, programs={})
        bp = [f for f in report["findings"]
              if f["category"] == "serving_backpressure"]
        assert bp and bp[0]["evidence"]["rejected"] == 50

    def test_serving_slo_and_memory_findings(self):
        snap = {
            "counters": {
                "serve_requests_total": [
                    _ctr("serve_requests_total", 100, status="submitted"),
                    _ctr("serve_requests_total", 30, status="expired"),
                ],
                "memory_pressure_total": [_ctr("memory_pressure_total", 2)],
            },
            "gauges": {}, "histograms": {},
        }
        report = doctor(snapshot=snap, trace=None, programs={})
        cats = [f["category"] for f in report["findings"]]
        assert "serving_slo" in cats and "memory_pressure" in cats

    def test_low_mfu_from_offline_snapshot_gauges(self):
        # Offline perf_doctor runs with an empty registry; the mfu check
        # must still work from the exported program_mfu /
        # program_expected_mfu gauges.
        snap = {
            "counters": {}, "histograms": {},
            "gauges": {
                "program_mfu": [
                    {"labels": {"program": "bench:gpt2"}, "value": 0.1}],
                "program_hfu": [
                    {"labels": {"program": "bench:gpt2"}, "value": 0.3}],
                "program_expected_mfu": [
                    {"labels": {"program": "bench:gpt2"}, "value": 0.5}],
            },
        }
        report = doctor(snapshot=snap, trace=None, programs={})
        m = [f for f in report["findings"] if f["category"] == "low_mfu"]
        assert m and m[0]["evidence"]["program"] == "bench:gpt2"

    def test_low_overlap_from_offline_trace_report(self):
        # merge_timelines(feed_metrics=False) never feeds the gauge; the
        # overlap section of the report must carry the finding offline —
        # but only with enough EXEC spans to mean anything.
        trace = dict(_fixture_trace_report())
        trace["overlap"] = {
            "by_rank": {"0": {"exec_spans": 8, "overlap_efficiency": 0.0},
                        "1": {"exec_spans": 8, "overlap_efficiency": 0.0}},
            "overlap_efficiency": 0.0,
        }
        empty = {"counters": {}, "gauges": {}, "histograms": {}}
        report = doctor(snapshot=empty, trace=trace, programs={})
        assert [f for f in report["findings"]
                if f["category"] == "low_overlap"]
        # a 3-collective smoke (too few spans) is not an overlap signal
        trace["overlap"]["by_rank"] = {
            "0": {"exec_spans": 3, "overlap_efficiency": 0.0}}
        report = doctor(snapshot=empty, trace=trace, programs={})
        assert not [f for f in report["findings"]
                    if f["category"] == "low_overlap"]

    def test_uncompressed_wire_suggests_quantization(self):
        snap = {
            "counters": {"allreduce_wire_bytes_total": [
                _ctr("allreduce_wire_bytes_total", 48 * 1024 * 1024,
                     algorithm="chunked_rs_ag", wire="fp32"),
            ]},
            "gauges": {}, "histograms": {}, "pending_collectives": [],
        }
        rep = doctor(snapshot=snap, trace=None, programs={})
        wire = [f for f in rep["findings"]
                if f["category"] == "wire_uncompressed"]
        assert len(wire) == 1
        assert "HOROVOD_ALLREDUCE_WIRE=int8" in wire[0]["suggestion"]
        assert "error_feedback" in wire[0]["suggestion"]

    def test_quantized_wire_reports_achieved_compression(self):
        snap = {
            "counters": {"allreduce_wire_bytes_total": [
                _ctr("allreduce_wire_bytes_total", 13 * 1024 * 1024,
                     algorithm="chunked_rs_ag_int8", wire="int8"),
                _ctr("allreduce_wire_bytes_total", 1 * 1024 * 1024,
                     algorithm="psum", wire="fp32"),
            ]},
            "gauges": {"allreduce_compression_ratio": [
                {"labels": {"wire": "int8"}, "value": 3.94},
            ]},
            "histograms": {}, "pending_collectives": [],
        }
        rep = doctor(snapshot=snap, trace=None, programs={})
        wire = [f for f in rep["findings"]
                if f["category"] == "wire_compression"]
        assert len(wire) == 1
        assert "3.9x" in wire[0]["title"]
        assert rep["healthy"]           # informational, not a defect
        # no double finding: the uncompressed suggestion must not fire
        assert not [f for f in rep["findings"]
                    if f["category"] == "wire_uncompressed"]

    def test_small_uncompressed_traffic_is_quiet(self):
        snap = {
            "counters": {"allreduce_wire_bytes_total": [
                _ctr("allreduce_wire_bytes_total", 1024,
                     algorithm="psum", wire="fp32"),
            ]},
            "gauges": {}, "histograms": {}, "pending_collectives": [],
        }
        rep = doctor(snapshot=snap, trace=None, programs={})
        assert not [f for f in rep["findings"]
                    if f["category"].startswith("wire")]

    def test_format_report_renders_every_finding(self):
        report = doctor(snapshot=_fixture_snapshot(),
                        trace=_fixture_trace_report(),
                        programs=_fixture_programs())
        text = format_report(report)
        for f in report["findings"]:
            assert f["title"] in text
            assert f["suggestion"] in text

    def test_report_is_json_serializable(self):
        report = doctor(snapshot=_fixture_snapshot(),
                        trace=_fixture_trace_report(),
                        programs=_fixture_programs())
        assert json.loads(json.dumps(report)) is not None

    def test_trace_accepts_merged_doc_and_path(self, tmp_path):
        merged = {"traceEvents": [],
                  "stragglerReport": _fixture_trace_report()}
        r1 = doctor(snapshot={"counters": {}, "gauges": {},
                              "histograms": {}},
                    trace=merged, programs={})
        path = tmp_path / "merged.json"
        path.write_text(json.dumps(merged))
        r2 = doctor(snapshot={"counters": {}, "gauges": {},
                              "histograms": {}},
                    trace=str(path), programs={})
        assert [f["category"] for f in r1["findings"]] == \
            [f["category"] for f in r2["findings"]] != []


def _gau(value, **labels):
    return {"labels": labels, "value": value}


class TestShardingCheck:
    """_check_sharding: replicated params + memory-bound symptoms →
    suggest HOROVOD_MESH (ISSUE 14 satellite)."""

    def _snap(self, **gauges):
        base = {"counters": {}, "gauges": {}, "histograms": {},
                "pending_collectives": []}
        base["gauges"].update(gauges)
        return base

    def test_peak_hbm_near_limit_suggests_mesh(self):
        snap = self._snap(
            config_mesh_dp=[_gau(8.0)], config_mesh_mp=[_gau(1.0)],
            device_hbm_bytes_limit=[_gau(100.0, device="0")],
            program_peak_hbm_bytes=[_gau(90.0, program="train_step")])
        report = doctor(snapshot=snap, trace=None, programs={})
        fs = [f for f in report["findings"]
              if f["category"] == "sharding"]
        assert fs and "train_step" in fs[0]["title"]
        assert "HOROVOD_MESH=dp4xmp2" in fs[0]["suggestion"]
        assert fs[0]["evidence"]["peak_hbm_bytes"] == 90.0

    def test_quiet_when_already_model_sharded(self):
        snap = self._snap(
            config_mesh_dp=[_gau(4.0)], config_mesh_mp=[_gau(2.0)],
            device_hbm_bytes_limit=[_gau(100.0, device="0")],
            program_peak_hbm_bytes=[_gau(99.0, program="train_step")])
        report = doctor(snapshot=snap, trace=None, programs={})
        assert not [f for f in report["findings"]
                    if f["category"] == "sharding"]

    def test_quiet_when_headroom(self):
        snap = self._snap(
            config_mesh_dp=[_gau(8.0)], config_mesh_mp=[_gau(1.0)],
            device_hbm_bytes_limit=[_gau(100.0, device="0")],
            program_peak_hbm_bytes=[_gau(50.0, program="train_step")])
        report = doctor(snapshot=snap, trace=None, programs={})
        assert not [f for f in report["findings"]
                    if f["category"] == "sharding"]

    def test_kv_quant_rejections_suggest_mesh(self):
        snap = self._snap(
            config_mesh_dp=[_gau(2.0)], config_mesh_mp=[_gau(1.0)],
            serve_kv_quant_enabled=[_gau(1.0, engine="e0")],
            serve_kv_pool_bytes_capacity=[_gau(4096.0, engine="e0")])
        snap["counters"]["serve_requests_total"] = [
            {"labels": {"engine": "e0", "status": "rejected"},
             "value": 3}]
        report = doctor(snapshot=snap, trace=None, programs={})
        fs = [f for f in report["findings"]
              if f["category"] == "sharding"]
        assert fs and fs[0]["evidence"]["rejected"] == 3
        assert "HOROVOD_MESH=dp1xmp2" in fs[0]["suggestion"]

    def test_no_kv_finding_without_quant(self):
        snap = self._snap(
            config_mesh_dp=[_gau(2.0)], config_mesh_mp=[_gau(1.0)],
            serve_kv_quant_enabled=[_gau(0.0, engine="e0")],
            serve_kv_pool_bytes_capacity=[_gau(4096.0, engine="e0")])
        snap["counters"]["serve_requests_total"] = [
            {"labels": {"engine": "e0", "status": "rejected"},
             "value": 3}]
        report = doctor(snapshot=snap, trace=None, programs={})
        assert not [f for f in report["findings"]
                    if f["category"] == "sharding"]

    def test_healthy_is_quiet(self):
        report = doctor(snapshot=self._snap(), trace=None, programs={})
        assert not [f for f in report["findings"]
                    if f["category"] == "sharding"]


class TestPerfDoctorCLI:
    def _import_tool(self):
        sys.path.insert(0, os.path.join(_REPO, "tools"))
        try:
            import perf_doctor
        finally:
            sys.path.remove(os.path.join(_REPO, "tools"))
        return perf_doctor

    def test_merge_snapshots_concatenates_series(self, tmp_path):
        perf_doctor = self._import_tool()
        a = tmp_path / "a.json"
        b = tmp_path / "b.json"
        a.write_text(json.dumps({
            "counters": {"x_total": [_ctr("x_total", 1, rank="0")]},
            "pending_collectives": [{"tensor": "t"}]}))
        b.write_text(json.dumps({
            "counters": {"x_total": [_ctr("x_total", 2, rank="1")]}}))
        merged = perf_doctor._merge_snapshots([str(a), str(b)])
        assert len(merged["counters"]["x_total"]) == 2
        assert merged["pending_collectives"] == [{"tensor": "t"}]

    def test_cli_exit_codes(self, tmp_path):
        sick = tmp_path / "sick.json"
        sick.write_text(json.dumps(_fixture_snapshot()))
        healthy = tmp_path / "ok.json"
        healthy.write_text(json.dumps(
            {"counters": {}, "gauges": {}, "histograms": {}}))
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        tool = os.path.join(_REPO, "tools", "perf_doctor.py")
        r = subprocess.run(
            [sys.executable, tool, "--metrics", str(sick), "--json"],
            capture_output=True, text=True, timeout=240, env=env)
        assert r.returncode == 2, r.stdout + r.stderr
        doc = json.loads(r.stdout)
        assert [f for f in doc["findings"] if f["category"] == "recompile"]
        r = subprocess.run(
            [sys.executable, tool, "--metrics", str(healthy)],
            capture_output=True, text=True, timeout=240, env=env)
        assert r.returncode == 0, r.stdout + r.stderr


# ---------------------------------------------------------------------------
# two-process doctor smoke (make doctor-smoke)
# ---------------------------------------------------------------------------

class TestTwoProcessSmoke:
    def test_doctor_smoke_two_process(self, tmp_path):
        """Acceptance drive: 2 real processes, a manufactured 250ms
        straggler and a forced recompile; hvd.doctor() must rank both and
        name the blamed argument (tools/doctor_smoke.py, also
        `make doctor-smoke`)."""
        r = subprocess.run(
            [sys.executable,
             os.path.join(_REPO, "tools", "doctor_smoke.py")],
            capture_output=True, text=True, timeout=500)
        assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
        assert "doctor-smoke OK" in r.stdout


# ---------------------------------------------------------------------------
# request-tail triage from the request-trace report (PR 15)
# ---------------------------------------------------------------------------

def _rreport(dominant, mean, *, blame=None, hedged=0, worst=None):
    return {
        "count": 4, "hedged": hedged,
        "ttft_p50_s": sum(mean.values()), "ttft_p99_s": sum(mean.values()),
        "breakdown_mean_s": mean, "dominant_component": dominant,
        "replica_blame_s": blame or {}, "dominant_replica": worst,
        "requests": [],
    }


_EMPTY_SNAP = {"counters": {}, "gauges": {}, "histograms": {}}


class TestRequestTailFindings:
    def test_queue_dominated_names_slots_knob(self):
        rep = doctor(snapshot=_EMPTY_SNAP, programs={}, trace={
            "requestReport": _rreport("queue", {
                "queue": 0.08, "prefill": 0.01, "decode": 0.005,
                "push": 0.0, "hedge_wait": 0.0, "other": 0.005})})
        tail = [f for f in rep["findings"]
                if f["category"] == "request_tail"]
        assert tail and tail[0]["evidence"]["dominant"] == "queue"
        assert "HOROVOD_SERVE_SLOTS" in tail[0]["suggestion"]
        assert tail[0]["evidence"]["fraction"] == pytest.approx(0.8)

    def test_hedge_wait_dominated_blames_replica(self):
        rep = doctor(snapshot=_EMPTY_SNAP, programs={}, trace={
            "requestReport": _rreport(
                "hedge_wait",
                {"queue": 0.005, "prefill": 0.01, "decode": 0.005,
                 "push": 0.0, "hedge_wait": 0.09, "other": 0.0},
                blame={"r0": 0.36, "r1": 0.02}, hedged=3, worst="r0")})
        tail = [f for f in rep["findings"]
                if f["category"] == "request_tail"]
        assert tail and tail[0]["evidence"]["slow_replica"] == "r0"
        assert "r0" in tail[0]["title"]
        assert tail[0]["evidence"]["hedged"] == 3

    def test_prefill_dominated_stays_quiet(self):
        # prefill/decode dominance is the model doing work — the triage
        # only fires for queue / push / hedge_wait (actionable waits).
        rep = doctor(snapshot=_EMPTY_SNAP, programs={}, trace={
            "requestReport": _rreport("prefill", {
                "queue": 0.001, "prefill": 0.2, "decode": 0.05,
                "push": 0.001, "hedge_wait": 0.0, "other": 0.002})})
        assert not [f for f in rep["findings"]
                    if f["category"] == "request_tail"]

    def test_slo_burn_cites_traced_breakdown(self):
        snap = {
            "counters": {
                "serve_requests_total": [
                    _ctr("serve_requests_total", 100, status="submitted"),
                    _ctr("serve_requests_total", 30, status="expired"),
                ],
            },
            "gauges": {}, "histograms": {},
        }
        rep = doctor(snapshot=snap, programs={}, trace={
            "requestReport": _rreport("queue", {
                "queue": 0.08, "prefill": 0.01, "decode": 0.005,
                "push": 0.0, "hedge_wait": 0.0, "other": 0.005})})
        slo = [f for f in rep["findings"] if f["category"] == "serving_slo"]
        assert slo and "queue 80.0ms" in slo[0]["detail"]
