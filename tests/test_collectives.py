"""Collective parity tests vs numpy (mirrors upstream
``test/parallel/test_tensorflow.py::test_horovod_allreduce*`` strategy)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu as hvd

N = 8


def stacked(rng, shape=(4, 3), dtype=np.float32):
    return rng.standard_normal((N,) + shape).astype(dtype)


# ---------------------------------------------------------------------------
# eager (stacked) collectives
# ---------------------------------------------------------------------------

class TestEagerAllreduce:
    def test_average(self, rng):
        x = stacked(rng)
        out = np.asarray(hvd.allreduce(x))
        want = np.broadcast_to(x.mean(axis=0), x.shape)
        np.testing.assert_allclose(out, want, rtol=1e-5)

    def test_sum(self, rng):
        x = stacked(rng)
        out = np.asarray(hvd.allreduce(x, op=hvd.Sum))
        np.testing.assert_allclose(out, np.broadcast_to(x.sum(0), x.shape),
                                   rtol=1e-5)

    def test_min_max(self, rng):
        x = stacked(rng)
        np.testing.assert_allclose(
            np.asarray(hvd.allreduce(x, op=hvd.Min)),
            np.broadcast_to(x.min(0), x.shape), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(hvd.allreduce(x, op=hvd.Max)),
            np.broadcast_to(x.max(0), x.shape), rtol=1e-6)

    def test_product(self, rng):
        x = stacked(rng, shape=(2, 2))
        out = np.asarray(hvd.allreduce(x, op=hvd.Product))
        np.testing.assert_allclose(out, np.broadcast_to(np.prod(x, 0), x.shape),
                                   rtol=1e-4)

    def test_prescale_postscale(self, rng):
        x = stacked(rng)
        out = np.asarray(hvd.allreduce(x, op=hvd.Sum, prescale_factor=0.5,
                                       postscale_factor=3.0))
        np.testing.assert_allclose(
            out, np.broadcast_to(3.0 * (0.5 * x).sum(0), x.shape), rtol=1e-5)

    def test_compression_fp16(self, rng):
        x = stacked(rng).astype(np.float32)
        out = np.asarray(hvd.allreduce(x, compression=hvd.Compression.fp16))
        assert out.dtype == np.float32
        np.testing.assert_allclose(out, np.broadcast_to(x.mean(0), x.shape),
                                   rtol=1e-2, atol=1e-2)

    def test_int_dtype_sum(self, rng):
        x = rng.integers(-5, 5, size=(N, 4)).astype(np.int32)
        out = np.asarray(hvd.allreduce(x, op=hvd.Sum))
        np.testing.assert_array_equal(out, np.broadcast_to(x.sum(0), x.shape))

    def test_pytree(self, rng):
        tree = {"a": stacked(rng), "b": [stacked(rng, (2,)), stacked(rng, (5, 1))]}
        out = hvd.allreduce(tree, op=hvd.Sum)
        np.testing.assert_allclose(np.asarray(out["a"]),
                                   np.broadcast_to(tree["a"].sum(0),
                                                   tree["a"].shape), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(out["b"][1]),
                                   np.broadcast_to(tree["b"][1].sum(0),
                                                   tree["b"][1].shape),
                                   rtol=1e-5)

    def test_grouped(self, rng):
        ts = [stacked(rng), stacked(rng, (7,))]
        outs = hvd.grouped_allreduce(ts, op=hvd.Average)
        assert len(outs) == 2
        for t, o in zip(ts, outs):
            np.testing.assert_allclose(np.asarray(o),
                                       np.broadcast_to(t.mean(0), t.shape),
                                       rtol=1e-5)

    def test_adasum_two_rank_closed_form(self, rng):
        ps = hvd.add_process_set([0, 1])  # adasum only global; use global n=8
        hvd.remove_process_set(ps)
        # 8-rank adasum: verify against host-side recursive doubling.
        x = stacked(rng, (6,))
        out = np.asarray(hvd.allreduce(x, op=hvd.Adasum))

        def combine(a, b):
            dot, asq, bsq = a @ b, a @ a, b @ b
            ca = 1 - dot / (2 * asq) if asq > 0 else 1.0
            cb = 1 - dot / (2 * bsq) if bsq > 0 else 1.0
            return ca * a + cb * b

        ref = [x[i].astype(np.float64) for i in range(N)]
        d = 1
        while d < N:
            ref = [combine(ref[i], ref[i ^ d]) for i in range(N)]
            d *= 2
        for i in range(N):
            np.testing.assert_allclose(out[i], ref[i], rtol=1e-4, atol=1e-5)


class TestEagerOtherCollectives:
    def test_broadcast(self, rng):
        x = stacked(rng)
        out = np.asarray(hvd.broadcast(x, root_rank=3))
        np.testing.assert_allclose(out, np.broadcast_to(x[3], x.shape),
                                   rtol=1e-6)

    def test_allgather(self, rng):
        x = stacked(rng, (2, 3))
        out = np.asarray(hvd.allgather(x))  # (N, N*2, 3)
        want = x.reshape(N * 2, 3)
        for r in range(N):
            np.testing.assert_allclose(out[r], want, rtol=1e-6)

    def test_alltoall(self, rng):
        x = stacked(rng, (N, 5))  # rank r sends x[r, d] to rank d
        out = np.asarray(hvd.alltoall(x))  # (N, N, 5)
        for r in range(N):
            np.testing.assert_allclose(out[r], x[:, r, :], rtol=1e-6)

    def test_reducescatter(self, rng):
        x = stacked(rng, (N * 2, 3))
        out = np.asarray(hvd.reducescatter(x, op=hvd.Sum))  # (N, 2, 3)
        full = x.sum(0)
        for r in range(N):
            np.testing.assert_allclose(out[r], full[r * 2:(r + 1) * 2],
                                       rtol=1e-5)

    def test_barrier_and_join(self):
        hvd.barrier()
        assert hvd.join() == N - 1

    def test_async_synchronize(self, rng):
        x = stacked(rng)
        h = hvd.allreduce_async(x)
        out = hvd.synchronize(h)
        assert hvd.poll(h)
        np.testing.assert_allclose(np.asarray(out),
                                   np.broadcast_to(x.mean(0), x.shape),
                                   rtol=1e-5)

    def test_broadcast_object_single_process(self):
        obj = {"lr": 0.1, "steps": [1, 2]}
        assert hvd.broadcast_object(obj, 0) == obj
        assert hvd.allgather_object(obj) == [obj]


# ---------------------------------------------------------------------------
# process sets
# ---------------------------------------------------------------------------

class TestProcessSets:
    def test_allreduce_subset(self, rng):
        ps = hvd.add_process_set([1, 3, 5])
        try:
            x = stacked(rng)
            out = np.asarray(hvd.allreduce(x, op=hvd.Sum, process_set=ps))
            want = x[[1, 3, 5]].sum(0)
            for r in (1, 3, 5):
                np.testing.assert_allclose(out[r], want, rtol=1e-5)
            for r in (0, 2, 4, 6, 7):  # non-members keep their own value
                np.testing.assert_allclose(out[r], x[r], rtol=1e-6)
        finally:
            hvd.remove_process_set(ps)

    def test_broadcast_subset(self, rng):
        ps = hvd.add_process_set([0, 2, 4, 6])
        try:
            x = stacked(rng)
            out = np.asarray(hvd.broadcast(x, root_rank=2, process_set=ps))
            for r in (0, 2, 4, 6):
                np.testing.assert_allclose(out[r], x[2], rtol=1e-6)
            for r in (1, 3, 5, 7):
                np.testing.assert_allclose(out[r], x[r], rtol=1e-6)
        finally:
            hvd.remove_process_set(ps)

    def test_allgather_subset(self, rng):
        ps = hvd.add_process_set([2, 5])
        try:
            x = stacked(rng, (3,))
            out = np.asarray(hvd.allgather(x, process_set=ps))
            # Members get the members' values concatenated along axis 0.
            want = x[[2, 5]].reshape(6)
            assert out.shape == (N, 6)
            for r in (2, 5):
                np.testing.assert_allclose(out[r], want, rtol=1e-6)
            # Non-members must not observe members' data: zeros.
            for r in (0, 1, 3, 4, 6, 7):
                np.testing.assert_array_equal(out[r], np.zeros(6))
        finally:
            hvd.remove_process_set(ps)

    def test_reducescatter_subset(self, rng):
        ps = hvd.add_process_set([0, 4])
        try:
            x = stacked(rng, (4, 3))
            out = np.asarray(hvd.reducescatter(x, op=hvd.Sum, process_set=ps))
            full = x[[0, 4]].sum(0)
            np.testing.assert_allclose(out[0], full[:2], rtol=1e-5)
            np.testing.assert_allclose(out[4], full[2:], rtol=1e-5)
        finally:
            hvd.remove_process_set(ps)

    def test_set_bookkeeping(self):
        ps = hvd.add_process_set([1, 2])
        assert ps.size() == 2 and ps.included(1) and not ps.included(0)
        assert ps.rank(2) == 1
        ids = hvd.process_set.get_process_set_ids_and_ranks() \
            if hasattr(hvd, "process_set") else None
        assert hvd.remove_process_set(ps)
        assert not hvd.remove_process_set(hvd.global_process_set())

    def test_invalid_sets(self):
        with pytest.raises(ValueError):
            hvd.add_process_set([0, 0])
        with pytest.raises(ValueError):
            hvd.add_process_set([99])


class _FakeKVClient:
    """Dict-backed stand-in for the jax coordination-service KV client —
    just the four calls the subset barrier uses."""

    def __init__(self):
        self.store = {}

    def key_value_set(self, key, value, allow_overwrite=False):
        self.store[key] = value

    def key_value_delete(self, key):
        self.store.pop(key, None)

    def key_value_dir_get(self, prefix):
        return [(k, v) for k, v in self.store.items()
                if k.startswith(prefix + "/")]

    def key_value_try_get(self, key):
        return self.store.get(key)


class TestSubsetBarrierTeardown:
    def test_destroy_deletes_both_standing_epoch_marks(self, monkeypatch):
        # A member at epoch e still owns marks at e AND e-1 (e-2 is
        # cleaned on entry); remove_process_set must delete both, or a
        # later set reusing the id inherits ghost arrivals.
        from jax._src import distributed

        from horovod_tpu import collective

        fake = _FakeKVClient()
        monkeypatch.setattr(distributed.global_state, "client", fake)
        ps = hvd.add_process_set([0, 1])
        me = jax.process_index()
        try:
            for _ in range(3):   # epochs 1..3: e-2 cleanup kicks in at 3
                collective._subset_barrier_wait(ps, [me], timeout_s=5.0)
            assert collective._SUBSET_BARRIER_SEQ[ps.process_set_id] == 3
            standing = [k for k in fake.store
                        if k.startswith(f"hvdtpu_ps{ps.process_set_id}_")]
            # Entering epoch 3 deleted epoch 1's mark; 2 and 3 stand.
            assert sorted(standing) == [
                f"hvdtpu_ps{ps.process_set_id}_a2/{me}",
                f"hvdtpu_ps{ps.process_set_id}_a3/{me}"]
        finally:
            assert hvd.remove_process_set(ps)
        leaked = [k for k in fake.store
                  if k.startswith(f"hvdtpu_ps{ps.process_set_id}_")]
        assert leaked == [], f"teardown leaked barrier marks: {leaked}"
        assert ps.process_set_id not in collective._SUBSET_BARRIER_SEQ

    def test_teardown_without_barriers_is_a_noop(self, monkeypatch):
        from jax._src import distributed

        from horovod_tpu import collective

        fake = _FakeKVClient()
        monkeypatch.setattr(distributed.global_state, "client", fake)
        ps = hvd.add_process_set([0, 2])
        assert hvd.remove_process_set(ps)
        assert fake.store == {}
        assert ps.process_set_id not in collective._SUBSET_BARRIER_SEQ


# ---------------------------------------------------------------------------
# in-trace (SPMD) collectives
# ---------------------------------------------------------------------------

class TestInTrace:
    def test_allreduce_inside_spmd(self, rng):
        x = stacked(rng)

        def step(xs):
            return hvd.allreduce(xs, op=hvd.Average)

        fn = hvd.spmd(step, in_specs=jax.sharding.PartitionSpec("hvd"),
                      out_specs=jax.sharding.PartitionSpec("hvd"))
        out = np.asarray(fn(x))
        np.testing.assert_allclose(out, np.broadcast_to(x.mean(0), x.shape),
                                   rtol=1e-5)

    def test_rank_inside_spmd(self):
        def step(x):
            return x * 0 + hvd.rank()

        fn = hvd.spmd(step, in_specs=jax.sharding.PartitionSpec("hvd"),
                      out_specs=jax.sharding.PartitionSpec("hvd"))
        out = np.asarray(fn(jnp.zeros((N, 1), jnp.int32)))
        np.testing.assert_array_equal(out[:, 0], np.arange(N))

    def test_grad_sync_inside_spmd(self, rng):
        w = jnp.asarray(rng.standard_normal(4).astype(np.float32))
        data = stacked(rng, (4,))

        def step(w, x):
            g = hvd.grad(lambda w: jnp.sum((w * x) ** 2))(w)
            return g

        fn = hvd.spmd(step,
                      in_specs=(jax.sharding.PartitionSpec(),
                                jax.sharding.PartitionSpec("hvd")),
                      out_specs=jax.sharding.PartitionSpec())
        g = np.asarray(fn(w, data))
        want = np.mean([2 * (np.asarray(w) * data[r] ** 2)
                        for r in range(N)], axis=0)
        np.testing.assert_allclose(g, want, rtol=1e-4)


class TestFusion:
    def test_fuse_roundtrip(self, rng):
        from horovod_tpu import fusion
        leaves = [rng.standard_normal((3, 2)).astype(np.float32),
                  rng.integers(0, 5, (4,)).astype(np.int32),
                  rng.standard_normal((1,)).astype(np.float32),
                  rng.standard_normal((2, 2, 2)).astype(np.float32)]
        buckets, unpack = fusion.fuse([jnp.asarray(x) for x in leaves])
        # fp32 leaves fuse together; int leaf has its own bucket
        assert len(buckets) == 2
        out = unpack(buckets)
        for a, b in zip(leaves, out):
            np.testing.assert_array_equal(a, np.asarray(b))

    def test_threshold_splits_buckets(self, rng):
        from horovod_tpu import fusion
        # 100 fp32 = 400 B, padded to the 512 B tile stride for capacity
        # accounting -> two per 1024 B bucket.
        leaves = [jnp.ones((100,), jnp.float32) for _ in range(4)]
        buckets, unpack = fusion.fuse(leaves, threshold_bytes=1024)
        assert len(buckets) == 2
        out = unpack(buckets)
        assert all(np.asarray(o).shape == (100,) for o in out)

    def test_python_fallback_matches_native_plan(self):
        from horovod_tpu import fusion, native
        if not native.native_available():
            return
        sizes = [100, 400, 900, 512, 513, 4096, 1, 511]
        nat = native.fusion_plan(sizes, 2048,
                                 align_bytes=fusion.FUSION_ALIGN_BYTES)
        import unittest.mock as mock
        with mock.patch.object(native, "fusion_plan", return_value=None):
            py = fusion._plan_buckets(sizes, 2048)
        assert nat == py


# ---------------------------------------------------------------------------
# gradient accumulation (backward_passes_per_step)
# ---------------------------------------------------------------------------

class TestBackwardPassesPerStep:
    def test_accumulates_then_applies_synced_average(self, rng):
        import optax
        params = {"w": jnp.asarray(rng.standard_normal(4), jnp.float32)}
        g1 = {"w": jnp.asarray(rng.standard_normal(4), jnp.float32)}
        g2 = {"w": jnp.asarray(rng.standard_normal(4), jnp.float32)}

        opt = hvd.DistributedOptimizer(optax.sgd(1.0),
                                       backward_passes_per_step=2)
        state = opt.init(params)

        u1, state = opt.update(g1, state, params)
        # Accumulation step: no update applied yet.
        np.testing.assert_allclose(np.asarray(u1["w"]), 0.0)
        assert not bool(hvd.accumulation_has_updated(state))
        u2, state = opt.update(g2, state, params)
        # k-th step: sgd(1.0) update = -(g1 + g2) — upstream sums the k
        # accumulated passes before the (single-rank) allreduce.
        want = -(np.asarray(g1["w"]) + np.asarray(g2["w"]))
        np.testing.assert_allclose(np.asarray(u2["w"]), want, rtol=1e-6)
        assert bool(hvd.accumulation_has_updated(state))

    def test_invalid_k_raises(self):
        import optax
        with pytest.raises(ValueError, match="backward_passes_per_step"):
            hvd.DistributedOptimizer(optax.sgd(0.1),
                                     backward_passes_per_step=0)

    def test_works_inside_spmd(self, rng):
        import optax
        params = jnp.zeros((4,), jnp.float32)
        data = jnp.asarray(rng.standard_normal((8, 4)), jnp.float32)
        opt = hvd.DistributedOptimizer(optax.sgd(0.5),
                                       backward_passes_per_step=2)
        state = opt.init(params)

        def step(params, state, x):
            g = hvd.grad(lambda p: jnp.mean((x @ p - 1.0) ** 2))(params)
            u, state = opt.update(g, state, params)
            return optax.apply_updates(params, u), state

        from jax.sharding import PartitionSpec as P
        sstep = hvd.spmd(step, in_specs=(P(), P(), P("hvd")),
                         out_specs=(P(), P()))
        p1, state = sstep(params, state, data)
        np.testing.assert_allclose(np.asarray(p1), 0.0)  # accumulating
        p2, state = sstep(p1, state, data)
        assert float(jnp.max(jnp.abs(p2))) > 0  # k-th step applied


class TestGroupedVariants:
    def test_grouped_allgather(self, rng):
        xs = [rng.standard_normal((8, 3)).astype(np.float32),
              rng.standard_normal((8, 2, 2)).astype(np.float32)]
        outs = hvd.grouped_allgather(xs)
        assert len(outs) == 2
        for x, out in zip(xs, outs):
            # Each rank's row r gathers all ranks' rows -> (8, 8*rest...).
            want = np.stack([np.concatenate([x[i] for i in range(8)], 0)] * 8)
            np.testing.assert_allclose(np.asarray(out), want, rtol=1e-6)

    def test_grouped_reducescatter(self, rng):
        xs = [rng.standard_normal((8, 8, 2)).astype(np.float32)]
        outs = hvd.grouped_reducescatter(xs, op=hvd.Sum)
        (out,) = outs
        # Rank r receives the summed chunk r of axis 0 (8 rows / 8 ranks =
        # a (1, 2) chunk each).
        summed = np.asarray(xs[0]).sum(0)
        for r in range(8):
            np.testing.assert_allclose(np.asarray(out[r]), summed[r:r + 1],
                                       rtol=1e-5)
