"""Shared-prefix KV cache + speculative decode (ISSUE 12).

Pins the sharing contracts the engine relies on:

* radix index semantics — whole-block match, first-writer-wins insert,
  LRU leaf eviction gated on refcount;
* ``BlockManager`` sharing invariants under a randomized
  admit/write/register/release trace (``check()`` after every op);
* copy-on-write: a capped full-prefix match CoWs exactly the last
  attached block, and the non-CoW ``ensure()`` refuses shared writes;
* ``hvd.doctor()`` prefix/spec findings over canned snapshots;
* the full ``make prefix-smoke`` contract in-process — engine-level
  token parity for three families with the cache + speculative lane
  on, the hit/reuse counters and request metadata agreeing, and a
  leak-free pool after drain.
"""

import os
import sys

import numpy as np
import pytest

from horovod_tpu.profiler import doctor
from horovod_tpu.serving.cache import BlockManager, PrefixIndex

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# radix index (pure host structure)
# ---------------------------------------------------------------------------

class TestPrefixIndex:
    def test_match_insert_first_writer_wins(self):
        idx = PrefixIndex(4)
        assert idx.match([1, 2, 3, 4, 5]) == []
        assert idx.insert([1, 2, 3, 4, 5, 6, 7, 8], [3, 4]) == [3, 4]
        # whole-block semantics: partial last chunks never match
        assert idx.match([1, 2, 3, 4, 5, 6, 7, 8, 9]) == [3, 4]
        assert idx.match([1, 2, 3, 4, 9]) == [3]
        assert idx.match([9, 2, 3, 4]) == []
        # a re-publish of an indexed chunk must NOT steal the entry —
        # otherwise one block could end up indexed twice
        assert idx.insert([1, 2, 3, 4, 5, 6, 7, 8], [5, 6]) == []
        assert idx.match([1, 2, 3, 4, 5, 6, 7, 8]) == [3, 4]
        assert idx.num_nodes == 2 and set(idx.blocks()) == {3, 4}

    def test_evict_lru_leaf_only_refcount_gated(self):
        idx = PrefixIndex(2)
        refc = np.ones(10, np.int64)
        idx.insert([1, 2, 3, 4], [2, 3])       # chain 2 -> 3
        idx.insert([5, 6], [4])                # leaf 4, touched later
        # interior node 2 is never evictable while 3 exists; 3 is the
        # LRU leaf, then 2 becomes a leaf but 4 is still younger.
        assert idx.evict_lru(refc) == 3
        assert idx.evict_lru(refc) == 2
        refc[4] = 2                            # someone else holds it
        assert idx.evict_lru(refc) is None
        refc[4] = 1
        assert idx.evict_lru(refc) == 4
        assert idx.num_nodes == 0 and idx.evictions == 3


# ---------------------------------------------------------------------------
# BlockManager sharing invariants
# ---------------------------------------------------------------------------

class TestCopyOnWrite:
    def _prefill(self, mgr, slot, tokens, total):
        mgr.admit(slot, total)
        for p in range(len(tokens)):
            mgr.ensure_writable(slot, p)
        mgr.register_prefix(slot, tokens)

    def test_capped_full_match_single_cow(self):
        mgr = BlockManager(16, 4, 2, 8, prefix_cache=True)
        tokens = [1, 2, 3, 4, 5, 6, 7, 8]
        self._prefill(mgr, 0, tokens, total=10)
        mgr.release(0)
        assert mgr.check() is None

        # the prompt IS the indexed chain: match caps at len-1, so the
        # refeed's first write lands inside the LAST attached block
        n, attach = mgr.match_prefix(tokens)
        assert n == 7 and len(attach) == 2
        assert mgr.can_admit(9, n, attach)
        mgr.admit(1, 9, n, attach)
        with pytest.raises(RuntimeError, match="without CoW"):
            mgr.ensure(1, 7)
        pair = mgr.ensure_writable(1, 7)
        assert pair is not None and pair[0] == attach[1]
        assert mgr.cow_copies == 1
        # the index keeps the original; the slot now maps the copy
        assert int(mgr.table[1, 1]) == pair[1] != attach[1]
        assert mgr.check() is None
        mgr.release(1)
        assert mgr.check() is None

    def test_aligned_match_needs_no_cow(self):
        mgr = BlockManager(16, 4, 2, 8, prefix_cache=True)
        tokens = [1, 2, 3, 4, 5, 6, 7, 8]
        self._prefill(mgr, 0, tokens, total=10)
        mgr.release(0)
        longer = tokens + [9, 9, 9]
        n, attach = mgr.match_prefix(longer)
        assert n == 8 and len(attach) == 2
        mgr.admit(1, len(longer) + 4, n, attach)
        for p in range(n, len(longer) + 4):
            assert mgr.ensure_writable(1, p) is None
        assert mgr.cow_copies == 0
        assert mgr.check() is None

    def test_lru_eviction_under_pressure(self):
        # capacity 5, index ends up holding 4 blocks; a 3-block cold
        # admission must reclaim via LRU eviction, not fail
        mgr = BlockManager(6, 2, 1, 4, prefix_cache=True)
        for base in (1, 2):
            tokens = [base] * 4
            self._prefill(mgr, 0, tokens, total=4)
            mgr.release(0)
        assert mgr.prefix.num_nodes == 4 and len(mgr._free) == 1
        assert mgr.can_admit(6)
        mgr.admit(0, 6)
        for p in range(6):
            mgr.ensure_writable(0, p)
        assert mgr.prefix.evictions >= 2
        assert mgr.check() is None
        # evicted chains are really gone from the index
        n1, _ = mgr.match_prefix([1] * 4)
        n2, _ = mgr.match_prefix([2] * 4)
        assert mgr.prefix.num_nodes <= 2 and min(n1, n2) == 0

    def test_randomized_sharing_trace(self, rng):
        """ISSUE 12 satellite: admit/write/register/release in random
        order with a colliding-prefix workload; every sharing invariant
        (refcount == holders, disjoint free list, conservation,
        reservation solvency) must hold after EVERY op."""
        bs = 4
        mgr = BlockManager(20, bs, 4, 8, prefix_cache=True)
        active = {}
        for step in range(600):
            r = rng.random()
            free_slots = [s for s in range(4) if s not in active]
            if r < 0.35 and free_slots:
                plen = int(rng.integers(1, 13))
                tokens = [int(t) for t in rng.integers(1, 5, plen)]
                total = plen + int(rng.integers(1, 9))
                n, attach = mgr.match_prefix(tokens)
                if mgr.can_admit(total, n, attach):
                    slot = free_slots[0]
                    mgr.admit(slot, total, n, attach)
                    active[slot] = dict(tokens=tokens, total=total,
                                        pos=n, registered=False)
            elif r < 0.85 and active:
                slot = list(active)[int(rng.integers(len(active)))]
                st = active[slot]
                if st["pos"] < st["total"]:
                    mgr.ensure_writable(slot, st["pos"])
                    st["pos"] += 1
                    if (st["pos"] >= len(st["tokens"])
                            and not st["registered"]):
                        mgr.register_prefix(slot, st["tokens"])
                        st["registered"] = True
            elif active:
                slot = list(active)[int(rng.integers(len(active)))]
                mgr.release(slot)
                del active[slot]
            err = mgr.check()
            assert err is None, f"step {step}: {err}"
        for slot in list(active):
            mgr.release(slot)
        assert mgr.check() is None
        # after a full drain only the index holds blocks
        assert mgr.blocks_in_use == mgr.prefix.num_nodes
        stats = mgr.prefix_stats()
        assert stats["enabled"] and stats["lookups"] >= stats["hits"]
        assert 0.0 <= stats["hit_rate"] <= 1.0

    def test_disabled_prefix_is_the_old_reserve(self):
        mgr = BlockManager(16, 4, 2, 8)
        assert mgr.match_prefix([1, 2, 3, 4, 5]) == (0, [])
        assert mgr.prefix_stats()["enabled"] is False
        mgr.reserve(0, 8)
        for p in range(8):
            mgr.ensure(0, p)
        assert mgr.shared_block_count() == 0
        mgr.release(0)
        assert mgr.check() is None and mgr.blocks_in_use == 0


# ---------------------------------------------------------------------------
# doctor findings (canned snapshots)
# ---------------------------------------------------------------------------

def _g(value, engine):
    return {"labels": {"engine": engine}, "value": value}


class TestDoctorPrefix:
    def test_overlap_without_cache_suggests_enabling(self):
        snap = {"counters": {}, "histograms": {}, "gauges": {
            "serve_prompt_overlap_rate": [_g(0.6, "e0")]}}
        rep = doctor(snapshot=snap, trace=None, programs={})
        f = [x for x in rep["findings"]
             if x["category"] == "prefix_cache"]
        assert f and "HOROVOD_SERVE_PREFIX_CACHE" in f[0]["suggestion"]
        assert f[0]["evidence"]["overlap_rate"] == 0.6

    def test_low_hit_rate_suggests_bigger_pool(self):
        snap = {"counters": {}, "histograms": {}, "gauges": {
            "serve_prompt_overlap_rate": [_g(0.6, "e0")],
            "prefix_cache_hit_rate": [_g(0.1, "e0")],
            "prefix_cache_evictions": [_g(7, "e0")]}}
        rep = doctor(snapshot=snap, trace=None, programs={})
        f = [x for x in rep["findings"]
             if x["category"] == "prefix_cache"]
        assert f and "num_blocks" in f[0]["suggestion"]
        assert f[0]["evidence"]["evictions"] == 7

    def test_low_spec_acceptance_suggests_tuning_k(self):
        snap = {"histograms": {}, "gauges": {}, "counters": {
            "spec_tokens_proposed_total": [{"labels": {}, "value": 100}],
            "spec_tokens_accepted_total": [{"labels": {}, "value": 5}]}}
        rep = doctor(snapshot=snap, trace=None, programs={})
        f = [x for x in rep["findings"] if x["category"] == "spec_decode"]
        assert f and "HOROVOD_SERVE_SPEC_K" in f[0]["suggestion"]
        assert f[0]["evidence"]["proposed"] == 100

    def test_healthy_prefix_profile_is_quiet(self):
        snap = {"histograms": {}, "counters": {
            "spec_tokens_proposed_total": [{"labels": {}, "value": 100}],
            "spec_tokens_accepted_total": [{"labels": {}, "value": 60}]},
            "gauges": {
                "serve_prompt_overlap_rate": [_g(0.6, "e0")],
                "prefix_cache_hit_rate": [_g(0.5, "e0")]}}
        rep = doctor(snapshot=snap, trace=None, programs={})
        assert not [x for x in rep["findings"]
                    if x["category"] in ("prefix_cache", "spec_decode")]


# ---------------------------------------------------------------------------
# the full smoke contract (make prefix-smoke)
# ---------------------------------------------------------------------------

class TestPrefixSmoke:
    def test_prefix_smoke_in_process(self):
        sys.path.insert(0, os.path.join(_REPO, "tools"))
        try:
            import prefix_smoke
        finally:
            sys.path.remove(os.path.join(_REPO, "tools"))
        rc, text = prefix_smoke.run_smoke()
        assert rc == 0, text
