"""Convergence guardrails for the quantized allreduce wire (PR 6).

Training on a 1-byte wire is only safe with error feedback: the
quantizer's per-step error must be re-injected at the next step or it
accumulates as bias. Three guardrails pin that here:

* **MNIST loss-curve parity** — a short MnistCNN run with
  ``chunked_rs_ag_int8`` + error feedback must track the fp32-wire
  (psum) loss curve within tolerance (the acceptance criterion).
* **The no-error-feedback control** — a deterministic mixed-magnitude
  problem where one coordinate's gradient sets the int8 block scale and
  every other coordinate's gradient sits below half a quantization step:
  without error feedback those coordinates FREEZE (every step flushes
  their gradient to zero — exactly the failure the residual exists to
  prevent); with it they track the exact path within half a step.
* **GPT-2 step-loss check** — a tiny GPT2 config trained 3 steps on the
  int8 wire matches the fp32-wire step losses to ~1e-4 (transformer
  gradients are well-conditioned for block scaling).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd


def _run_train(params, loss_of_shard, batches, opt, steps):
    """Shared spmd train loop: ``batches`` is (n, ...) per-rank stacked
    data (sharded on axis 0), loss averaged across ranks for the curve."""
    state = opt.init(params)

    def step(p, s, b):
        l, g = jax.value_and_grad(loss_of_shard)(p, b)
        l = hvd.allreduce(l, op=hvd.Average)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s, l

    fn = hvd.spmd(step, in_specs=(P(), P(), P("hvd")),
                  out_specs=(P(), P(), P()))
    p, s = params, state
    losses = []
    for _ in range(steps):
        p, s, l = fn(p, s, batches)
        losses.append(float(l))
    return np.asarray(losses), p


class TestMnistLossCurveParity:
    STEPS = 10

    def _setup(self, rng):
        from horovod_tpu.models.mnist import MnistCNN
        n = hvd.size()
        model = MnistCNN()
        imgs = rng.standard_normal((n, 4, 14, 14, 1)).astype(np.float32)
        labels = rng.integers(0, 10, (n, 4)).astype(np.int32)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 14, 14, 1)), train=False)["params"]
        batches = (jnp.asarray(imgs), jnp.asarray(labels))

        def loss_of_shard(p, b):
            x, y = b[0][0], b[1][0]
            logits = model.apply({"params": p}, x, train=False)
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(jnp.take_along_axis(logp, y[:, None], 1))

        return params, loss_of_shard, batches

    def _train(self, setup, algorithm, error_feedback):
        params, loss_of_shard, batches = setup
        opt = hvd.DistributedOptimizer(optax.sgd(0.05, momentum=0.9),
                                       algorithm=algorithm,
                                       error_feedback=error_feedback)
        return _run_train(params, loss_of_shard, batches, opt, self.STEPS)

    def test_int8_with_error_feedback_matches_fp32_curve(self, rng):
        setup = self._setup(rng)         # ONE dataset for both runs
        ref, _ = self._train(setup, "psum", False)
        quant, _ = self._train(setup, "chunked_rs_ag_int8", True)
        # the run must actually learn, or "parity" is vacuous
        assert ref[-1] < 0.6 * ref[0]
        np.testing.assert_allclose(quant, ref, atol=0.08, err_msg=(
            "int8 wire + error feedback drifted from the fp32 loss "
            "curve"))

    def test_no_error_feedback_control_still_within_short_run_drift(
            self, rng):
        """On a SHORT run the uncompensated drift is small too — the
        control documenting the failure mode is the flush-regime test
        below, where the bias is systematic rather than noise."""
        setup = self._setup(rng)
        ref, _ = self._train(setup, "psum", False)
        noef, _ = self._train(setup, "chunked_rs_ag_int8", False)
        np.testing.assert_allclose(noef, ref, atol=0.15)


class TestWhyErrorFeedbackExists:
    """The no-EF control: gradients below half an int8 step of their
    block's max-abs flush to zero EVERY step — without the residual those
    coordinates never train."""

    D = 256          # one quantization block
    STEPS = 20
    LR = 0.01

    def _train(self, algorithm, error_feedback):
        c = np.full(self.D, 0.1, np.float32)
        c[0] = 100.0     # sets the block scale; half-step = 100/254 > 0.1
        c_j = jnp.asarray(c)
        w0 = jnp.zeros(self.D, jnp.float32)
        opt = hvd.DistributedOptimizer(optax.sgd(self.LR),
                                       algorithm=algorithm,
                                       error_feedback=error_feedback)
        _, w = _run_train(
            w0, lambda w, _b: jnp.dot(w, c_j),
            jnp.zeros((hvd.size(), 1), jnp.float32), opt, self.STEPS)
        return np.asarray(w)

    def test_flushed_coordinates_freeze_without_error_feedback(self):
        ref = self._train("psum", False)
        ef = self._train("chunked_rs_ag_int8", True)
        noef = self._train("chunked_rs_ag_int8", False)
        # exact path moves every coordinate by STEPS * LR * 0.1
        np.testing.assert_allclose(ref[1:], -self.STEPS * self.LR * 0.1,
                                   rtol=1e-5)
        # without the residual, the small-gradient coordinates are
        # FROZEN at exactly zero: every step quantized their gradient
        # to nothing.
        np.testing.assert_array_equal(noef[1:], 0.0)
        # with it, the accumulated residual crosses the quantization
        # step and the coordinates track the exact path within half an
        # int8 step's worth of drift.
        assert np.abs(ef[1:] - ref[1:]).max() < self.LR * (100.0 / 254)
        # the dominant coordinate trains identically either way
        np.testing.assert_allclose(ef[0], ref[0], rtol=1e-3)


class TestGpt2StepLoss:
    def test_tiny_gpt2_int8_step_losses_match(self, rng):
        from horovod_tpu.models.gpt2 import (GPT2, GPT2Config,
                                             loss_fn as gpt2_loss)
        n = hvd.size()
        cfg = GPT2Config.tiny()
        model = GPT2(cfg)
        toks = jnp.asarray(
            rng.integers(0, cfg.vocab_size, (n, 2, 32)), jnp.int32)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 32), jnp.int32))["params"]

        def loss_of_shard(p, t):
            logits = model.apply({"params": p}, t[0])
            return gpt2_loss(logits, t[0])

        def train(algorithm, error_feedback):
            opt = hvd.DistributedOptimizer(optax.adamw(1e-3),
                                           algorithm=algorithm,
                                           error_feedback=error_feedback)
            return _run_train(params, loss_of_shard, toks, opt, 3)[0]

        ref = train("psum", False)
        quant = train("chunked_rs_ag_int8", True)
        assert ref[-1] < ref[0]              # it learns
        np.testing.assert_allclose(quant, ref, atol=5e-3)
