"""Lightning strategy tests: the Strategy protocol + bundled Trainer loop
(upstream Lightning ``HorovodStrategy`` semantics, no PL dependency)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu.lightning import HorovodStrategy, Trainer  # noqa: E402


class BoringModule(torch.nn.Module):
    """LightningModule-shaped: training_step + configure_optimizers."""

    def __init__(self):
        super().__init__()
        self.net = torch.nn.Sequential(
            torch.nn.Linear(4, 8), torch.nn.ReLU(), torch.nn.Linear(8, 1))
        self.epochs_seen = 0

    def forward(self, x):
        return self.net(x)

    def training_step(self, batch, batch_idx):
        x, y = batch
        return torch.nn.functional.mse_loss(self(x), y)

    def configure_optimizers(self):
        return torch.optim.SGD(self.parameters(), lr=0.05)

    def on_epoch_end(self, trainer):
        self.epochs_seen += 1


def _loader(n=64, bs=16):
    rng = np.random.default_rng(1)
    x = torch.from_numpy(rng.normal(size=(n, 4)).astype(np.float32))
    y = x.sum(dim=1, keepdim=True)
    return [(x[i:i + bs], y[i:i + bs]) for i in range(0, n, bs)]


class TestStrategy:
    def test_identity(self):
        s = HorovodStrategy()
        assert s.world_size == hvd.size()
        assert s.global_rank == hvd.rank()
        assert s.is_global_zero == (hvd.rank() == 0)
        assert s.root_device.type == "cpu"

    def test_reduce_scalar_and_tensor(self):
        s = HorovodStrategy()
        out = s.reduce(3.0, reduce_op="mean")
        assert float(out) == pytest.approx(3.0, rel=1e-6)
        out = s.reduce(torch.ones(4), reduce_op="sum")
        assert torch.allclose(out, torch.full((4,), float(s.world_size)))

    def test_all_gather_stacks_world(self):
        s = HorovodStrategy()
        out = s.all_gather(torch.tensor([1.0, 2.0]))
        assert out.shape == (s.world_size, 2)
        assert torch.allclose(out[0], torch.tensor([1.0, 2.0]))

    def test_broadcast_object(self):
        s = HorovodStrategy()
        assert s.broadcast({"a": 1}, src=0) == {"a": 1}

    def test_setup_wraps_optimizers(self):
        s = HorovodStrategy()
        m = BoringModule()
        opts = s.setup(m)
        assert len(opts) == 1
        assert hasattr(opts[0], "synchronize")   # DistributedOptimizer

    def test_reduce_op_none_is_identity(self):
        s = HorovodStrategy()
        t = torch.tensor([1.0, 2.0])
        assert s.reduce(t, reduce_op=None) is t

    def test_configure_optimizers_forms(self):
        s = HorovodStrategy()
        m = BoringModule()
        opt = torch.optim.SGD(m.parameters(), lr=0.1)
        sched = torch.optim.lr_scheduler.StepLR(opt, 1)
        unpack = s._unpack_optimizers
        assert unpack(opt) == [opt]
        assert unpack([opt]) == [opt]
        assert unpack({"optimizer": opt, "lr_scheduler": sched}) == [opt]
        assert unpack(([opt], [sched])) == [opt]
        assert unpack(None) == []
        with pytest.raises(ValueError):
            unpack({"lr_scheduler": sched})
        with pytest.raises(TypeError):
            unpack([sched])


class TestTrainer:
    def test_fit_converges_and_hooks_fire(self):
        m = BoringModule()
        tr = Trainer(max_epochs=6).fit(m, _loader())
        assert len(tr.history) == 6
        assert tr.history[-1] < tr.history[0]
        assert m.epochs_seen == 6
