"""Flight recorder & postmortem plane (ISSUE 17): byte/age-bounded
rings under event storms, dump re-entrancy/debounce/token gating,
signal-safe dumps while another thread holds the metrics registry lock,
atomic bundle publication + oldest-first retention, alerts.jsonl size
rotation mirrored by the bundle's tail reader, and the offline
root-cause analyzer. No sleeps on the hot paths — rings take canned
timestamps."""

import json
import os
import threading
import time

import pytest

from horovod_tpu import blackbox, config, health, metrics, timeline
from horovod_tpu.blackbox import FlightRecorder, Ring


@pytest.fixture(autouse=True)
def _fresh():
    metrics.reset_metrics()
    blackbox.reset()
    yield
    blackbox.reset()
    for k in list(os.environ):
        if k.startswith("HOROVOD_BLACKBOX") or k == "HOROVOD_FAULTHANDLER":
            del os.environ[k]
    config.refresh()
    metrics.reset_metrics()


def _arm(tmp_path, **env):
    """Arm the process recorder onto a test-owned dir."""
    os.environ["HOROVOD_BLACKBOX"] = "1"
    os.environ["HOROVOD_BLACKBOX_DIR"] = str(tmp_path)
    for k, v in env.items():
        os.environ[k] = v
    config.refresh()
    rec = blackbox.ensure(rank=0, world=2)
    assert rec is not None
    return rec


class TestRing:
    def test_byte_bound_holds_under_storm(self):
        ring = Ring(max_bytes=1024, max_age_s=3600.0)
        for i in range(5000):
            ring.append("x" * 64, ts=1000.0 + i * 0.001)
        assert ring.nbytes <= 1024
        assert len(ring) == 1024 // 64
        assert ring.dropped == 5000 - 1024 // 64

    def test_eviction_is_strict_oldest_first(self):
        ring = Ring(max_bytes=10 * 8, max_age_s=3600.0)
        for i in range(100):
            ring.append(f"{i:08d}", ts=1000.0 + i)
        assert ring.items(now=1100.0) == [f"{i:08d}" for i in range(90, 100)]

    def test_age_bound_prunes_on_append_and_read(self):
        ring = Ring(max_bytes=1 << 20, max_age_s=10.0)
        ring.append({"i": 0}, ts=1000.0)
        ring.append({"i": 1}, ts=1009.0)
        ring.append({"i": 2}, ts=1012.0)   # i=0 is now 12s old
        assert [e["i"] for e in ring.items(now=1012.0)] == [1, 2]
        # a quiet ring drains to nothing: items() prunes age too
        assert ring.items(now=1050.0) == []
        assert ring.nbytes == 0


class TestDump:
    def test_dump_during_dump_refused_not_queued(self, tmp_path):
        rec = _arm(tmp_path)
        assert rec._dump_gate.acquire(blocking=False)
        try:
            assert rec.dump(trigger="manual") is None
        finally:
            rec._dump_gate.release()
        assert rec.dump(trigger="manual") is not None

    def test_auto_triggers_debounced_manual_not(self, tmp_path):
        rec = _arm(tmp_path)
        assert rec.dump(trigger="alert") is not None
        assert rec.dump(trigger="alert") is None      # < min interval
        assert rec.dump(trigger="manual") is not None  # forced

    def test_dump_on_token_gating(self, tmp_path):
        rec = _arm(tmp_path, HOROVOD_BLACKBOX_DUMP_ON="signal")
        assert rec.dump(trigger="alert") is None       # token off
        assert rec.dump(trigger="manual") is not None  # always allowed

    def test_dump_on_rejects_unknown_tokens(self):
        os.environ["HOROVOD_BLACKBOX_DUMP_ON"] = "signal,bogus"
        with pytest.raises(ValueError, match="bogus"):
            config.refresh()

    def test_dump_completes_with_registry_lock_held(self, tmp_path):
        """The signal-handler contract: a dump fired while ANOTHER
        thread holds the metrics registry lock must still publish a
        bundle — skipping the final live sample, deferring the
        dumps-total bump, and capturing every thread's stack."""
        rec = _arm(tmp_path)
        metrics.counter("probe_total").inc(3)
        rec.sampler.sample_once()                 # pre-sampled evidence
        acquired, release = threading.Event(), threading.Event()

        def hog():
            with metrics.registry._lock:
                acquired.set()
                release.wait(10.0)

        t = threading.Thread(target=hog, daemon=True)
        t.start()
        assert acquired.wait(5.0)
        try:
            bundle = rec.dump(trigger="signal")
        finally:
            release.set()
            t.join(5.0)
        assert bundle is not None and os.path.isdir(bundle)
        manifest = json.load(open(os.path.join(bundle, "manifest.json")))
        assert manifest["sampled_final"] is False
        stacks = open(os.path.join(bundle, "stacks.txt")).read()
        assert "hog" in stacks       # the lock holder's stack is there
        # the counter bump was deferred to a daemon thread, not dropped
        deadline = time.time() + 5.0
        while time.time() < deadline:
            snap = metrics.snapshot()
            series = snap.get("counters", {}).get("blackbox_dumps_total", [])
            if sum(s["value"] for s in series
                   if s.get("labels", {}).get("trigger") == "signal") >= 1:
                break
            time.sleep(0.02)
        else:
            pytest.fail("deferred blackbox_dumps_total bump never landed")

    def test_retention_evicts_oldest_first(self, tmp_path):
        rec = _arm(tmp_path, HOROVOD_BLACKBOX_MAX_BUNDLES="2")
        bundles = [rec.dump(trigger="manual", label=f"b{i}")
                   for i in range(3)]
        assert all(bundles)
        assert not os.path.isdir(bundles[0])
        assert os.path.isdir(bundles[1]) and os.path.isdir(bundles[2])

    def test_timeline_tap_installed_and_removed(self, tmp_path):
        rec = _arm(tmp_path)
        assert rec._tap_timeline in timeline._TAPS
        blackbox.reset()
        assert rec._tap_timeline not in timeline._TAPS

    def test_disabled_is_a_total_noop(self):
        assert blackbox.ensure() is None
        assert blackbox.dump_postmortem() is None
        blackbox.note_fault("kill", rank=0, step=1)       # must not raise
        blackbox.on_alert({"event": "fire", "severity": 1.0})


class TestAlertsRotation:
    def test_rotation_keeps_two_generations(self, tmp_path, monkeypatch):
        monkeypatch.setattr(health, "ALERTS_ROTATE_BYTES", 256)
        path = str(tmp_path / "alerts.jsonl")
        doc = health.ContinuousDoctor(alerts_path=path, sample_local=False)
        for i in range(40):
            doc._append_alert({"event": "fire", "finding": f"f{i}",
                               "severity": 0.5, "ts": 1000.0 + i})
        assert os.path.isfile(path + ".1")
        assert os.path.getsize(path) < 256 + 128       # base stays small
        assert not os.path.exists(path + ".2")         # only 2 generations
        # the bundle's tail reader spans the rotation boundary: the
        # records it returns are contiguous and end with the newest.
        tail = blackbox.read_alerts_tail(path)
        ids = [int(r["finding"][1:]) for r in tail]
        assert ids == list(range(ids[0], 40))
        assert len(ids) > sum(1 for _ in open(path))   # crossed into .1


class TestPostmortemReport:
    def test_crash_loop_ranked_first_with_blamed_rank(self, tmp_path):
        rec = _arm(tmp_path)
        metrics.counter("serve_requests_total").inc(5)
        rec.sampler.sample_once()
        blackbox.note_fault("crash_loop", rank=3, step=7,
                            detail="FAULT crash_loop@rank=3,step=7")
        blackbox.note_fleet("quarantine", replica="r3",
                            reason="crash_loop: 3 deaths in 120s")
        blackbox.on_alert({"event": "fire", "finding": "fleet_availability",
                           "severity": 0.6, "title": "fleet below target",
                           "ts": time.time()})
        bundle = blackbox.dump_postmortem(trigger="fault",
                                          note="FAULT crash_loop@rank=3")
        report = blackbox.postmortem_report(bundle)
        cause = report["cause"]
        assert cause["category"] == "crash_loop"
        assert "rank 3" in cause["title"]
        assert report["findings"][0]["rank"] == 1
        # ground truth supersedes the alert-before-death hypothesis:
        # no speculative alert finding when the fault event IS the cause,
        # but the alert record still rides in the bundle's events ring.
        assert all(f["category"] != "fleet_availability"
                   for f in report["findings"])
        events = [json.loads(line) for line in
                  open(os.path.join(bundle, "events.jsonl"))]
        assert any(e["type"] == "alert"
                   and e.get("finding") == "fleet_availability"
                   for e in events)
        assert report["stacks_present"]
        text = blackbox.format_postmortem(report)
        assert "root cause" in text and "crash_loop" in text

    def test_default_report_picks_newest_bundle(self, tmp_path):
        rec = _arm(tmp_path)
        rec.dump(trigger="manual", label="old")
        time.sleep(0.05)
        newest = rec.dump(trigger="manual", label="new")
        report = blackbox.postmortem_report(root=str(tmp_path))
        assert report["bundle"] == newest

    def test_no_bundles_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            blackbox.postmortem_report(root=str(tmp_path))


class TestBundleContents:
    def test_trace_tail_merges_and_window_feeds_doctor(self, tmp_path):
        rec = _arm(tmp_path)
        metrics.counter("demo_total").inc()
        rec.sampler.sample_once()
        rec._tap_timeline({"name": "allreduce", "ph": "X",
                           "ts": time.time() * 1e6, "dur": 10,
                           "pid": 0, "tid": 1, "args": {}})
        bundle = rec.dump(trigger="manual")
        # the trace dir is a valid shard set for the merger
        from horovod_tpu.timeline import merge_timelines
        merged = merge_timelines([os.path.join(bundle, "trace")],
                                 output=os.path.join(str(tmp_path),
                                                     "merged.json"))
        names = {e.get("name") for e in merged["traceEvents"]}
        assert "allreduce" in names
        # metrics.window.json is registry-snapshot-shaped: the offline
        # doctor accepts it unchanged
        window = json.load(open(os.path.join(bundle,
                                             "metrics.window.json")))
        from horovod_tpu import profiler
        report = profiler.doctor(snapshot=window, trace=None, programs={})
        assert "findings" in report
