// hvdtpu native runtime core.
//
// TPU-native rethink of the reference's C++ runtime layer
// (horovod/common/controller.cc, fusion_buffer_manager.cc,
// stall_inspector.cc, timeline.cc). On TPU the *device* schedule belongs to
// XLA, so this library owns only what the host genuinely controls:
//
//   1. Coordinator  — deterministic cross-process op ordering for the
//      multi-process eager path (bitvector readiness + rank-0 order, the
//      negotiation contract of the reference without the background thread:
//      the Python layer drives it synchronously at dispatch points).
//   2. Response cache — memoizes negotiated responses keyed by op name
//      (reference: response_cache.cc) so steady-state training skips
//      re-negotiation entirely.
//   3. Fusion planner — greedy bucket assignment under a byte threshold
//      with tile alignment (reference: fusion buffer offsets; here buckets
//      are concatenation plans handed back to XLA).
//   4. Stall inspector — tracks submit timestamps per (op, rank) and
//      reports ops missing ranks past a timeout (reference:
//      stall_inspector.cc one-sided health check).
//   5. Timeline appender — lock-protected chrome-trace JSON writer.
//
// Exposed as a C ABI for ctypes (no pybind11 in the image).

#include <algorithm>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

double now_us() {
  using namespace std::chrono;
  return duration_cast<duration<double, std::micro>>(
             steady_clock::now().time_since_epoch())
      .count();
}

struct OpState {
  std::vector<uint8_t> ready;   // per-rank submission bit
  std::vector<double> t_submit; // per-rank submit time (us), 0 = never
  int order = -1;               // rank-0 submission order
};

struct Coordinator {
  int world;
  std::mutex mu;
  std::unordered_map<std::string, OpState> ops;
  int next_order = 0;
  std::unordered_map<std::string, std::string> cache;  // response cache
};

struct TimelineW {
  FILE* f = nullptr;
  std::mutex mu;
  bool first = true;
  double t0 = 0;
};

}  // namespace

extern "C" {

// ---------------------------------------------------------------- coordinator
void* hvd_coord_create(int world_size) {
  auto* c = new Coordinator();
  c->world = world_size;
  return c;
}

void hvd_coord_destroy(void* h) { delete static_cast<Coordinator*>(h); }

// Submit op `name` from `rank`. Returns 1 if the op became ready (all ranks
// submitted), 0 otherwise, -1 on bad args.
int hvd_coord_submit(void* h, int rank, const char* name) {
  auto* c = static_cast<Coordinator*>(h);
  if (!c || rank < 0 || rank >= c->world || !name) return -1;
  std::lock_guard<std::mutex> g(c->mu);
  auto& op = c->ops[name];
  if (op.ready.empty()) {
    op.ready.assign(c->world, 0);
    op.t_submit.assign(c->world, 0.0);
  }
  if (!op.ready[rank]) {
    op.ready[rank] = 1;
    op.t_submit[rank] = now_us();
  }
  if (rank == 0 && op.order < 0) op.order = c->next_order++;
  int sum = 0;
  for (auto b : op.ready) sum += b;
  return sum == c->world ? 1 : 0;
}

// Pop the next ready op in rank-0 submission order (the reference's
// determinism guarantee: every rank executes collectives in the same order).
// Returns length written to buf, 0 if none ready, -1 on error. If the buffer
// is too small the op is NOT popped and -(needed_len+1) is returned so the
// caller can retry with a larger buffer.
int hvd_coord_pop_ready(void* h, char* buf, int buflen) {
  auto* c = static_cast<Coordinator*>(h);
  if (!c || !buf || buflen <= 0) return -1;
  std::lock_guard<std::mutex> g(c->mu);
  const std::string* best = nullptr;
  int best_order = INT32_MAX;
  for (auto& kv : c->ops) {
    auto& op = kv.second;
    if (op.order < 0) continue;  // rank 0 hasn't submitted: not ordered yet
    int sum = 0;
    for (auto b : op.ready) sum += b;
    if (sum == c->world && op.order < best_order) {
      best_order = op.order;
      best = &kv.first;
    }
  }
  if (!best) return 0;
  if (best->size() + 1 > (size_t)buflen) return -(int)(best->size() + 1);
  int n = (int)best->size();
  std::memcpy(buf, best->c_str(), n);
  buf[n] = 0;
  c->ops.erase(*best);
  return n;
}

// Count of ops submitted but not yet executed.
int hvd_coord_pending(void* h) {
  auto* c = static_cast<Coordinator*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  return (int)c->ops.size();
}

// --------------------------------------------------------------- resp. cache
void hvd_cache_put(void* h, const char* key, const char* value) {
  auto* c = static_cast<Coordinator*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  c->cache[key] = value;
}

// Returns the FULL value length (0 = miss) and writes up to buflen-1 bytes.
// A return >= buflen means the write was truncated: retry with a buffer of
// returned_length+1.
int hvd_cache_get(void* h, const char* key, char* buf, int buflen) {
  auto* c = static_cast<Coordinator*>(h);
  if (!c || !buf || buflen <= 0) return -1;
  std::lock_guard<std::mutex> g(c->mu);
  auto it = c->cache.find(key);
  if (it == c->cache.end()) return 0;
  int n = (int)std::min((size_t)buflen - 1, it->second.size());
  std::memcpy(buf, it->second.c_str(), n);
  buf[n] = 0;
  return (int)it->second.size();
}

int hvd_cache_size(void* h) {
  auto* c = static_cast<Coordinator*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  return (int)c->cache.size();
}

// -------------------------------------------------------------- fusion plan
// First-fit-decreasing bin packing for sequence packing
// (horovod_tpu/data/packing.py; the reference ecosystem packs in its C++
// data-loader workers). Documents are visited in decreasing-length order
// (ties broken by original index, matching the Python fallback exactly)
// and placed in the first open row with space; a new row opens when none
// fits. Writes each doc's row into row_of[i]; returns the number of rows
// used, or -1 on a bad argument (null pointer, n <= 0, or a length
// outside [0, row_len]). O(n * rows) first-fit scan — row counts are
// batch-sized, not corpus-sized.
int hvd_pack_ffd(const int64_t* lengths, int n, int64_t row_len,
                 int32_t* row_of) {
  if (!lengths || !row_of || n <= 0 || row_len <= 0) return -1;
  std::vector<int> order(n);
  for (int i = 0; i < n; ++i) order[i] = i;
  std::stable_sort(order.begin(), order.end(), [&](int a, int b) {
    return lengths[a] > lengths[b];
  });
  std::vector<int64_t> space;
  for (int idx : order) {
    const int64_t len = lengths[idx];
    if (len > row_len || len < 0) return -1;
    int placed = -1;
    for (size_t r = 0; r < space.size(); ++r) {
      if (space[r] >= len) { placed = static_cast<int>(r); break; }
    }
    if (placed < 0) {
      space.push_back(row_len);
      placed = static_cast<int>(space.size()) - 1;
    }
    space[placed] -= len;
    row_of[idx] = placed;
  }
  return static_cast<int>(space.size());
}

// Greedy assignment of tensors (by size in bytes, given order) into buckets
// of at most threshold bytes, each tensor padded to `align` bytes (TPU lane
// alignment). A tensor larger than the threshold gets its own bucket.
// out_buckets[i] = bucket index of tensor i. Returns bucket count.
int hvd_fusion_plan(const int64_t* sizes, int n, int64_t threshold,
                    int64_t align, int32_t* out_buckets) {
  if (!sizes || !out_buckets || n <= 0) return -1;
  if (align <= 0) align = 1;
  int64_t used = 0;
  int bucket = -1;
  for (int i = 0; i < n; i++) {
    int64_t sz = (sizes[i] + align - 1) / align * align;
    if (bucket < 0 || used + sz > threshold) {
      bucket++;
      used = 0;
    }
    out_buckets[i] = bucket;
    used += sz;
  }
  return bucket + 1;
}

// ------------------------------------------------------------ stall inspect
// Report ops stuck longer than timeout_us: an op is stuck if at least one
// rank submitted and at least one hasn't, and the oldest submission is older
// than the timeout. Writes "name:missing_count;..." into buf. Returns the
// number of stuck ops, or -(needed_len+1) if the buffer is too small for the
// full report (nothing useful is written in that case; retry larger).
int hvd_stall_check(void* h, double timeout_us, char* buf, int buflen) {
  auto* c = static_cast<Coordinator*>(h);
  if (!c || !buf || buflen <= 0) return -1;
  std::lock_guard<std::mutex> g(c->mu);
  double now = now_us();
  std::string report;
  int count = 0;
  for (auto& kv : c->ops) {
    auto& op = kv.second;
    int sum = 0;
    double oldest = 0;
    for (int r = 0; r < c->world; r++) {
      if (op.ready[r]) {
        sum++;
        if (oldest == 0 || op.t_submit[r] < oldest) oldest = op.t_submit[r];
      }
    }
    if (sum > 0 && sum < c->world && now - oldest > timeout_us) {
      count++;
      report += kv.first + ":" + std::to_string(c->world - sum) + ";";
    }
  }
  if (report.size() + 1 > (size_t)buflen) {
    buf[0] = 0;
    return -(int)(report.size() + 1);
  }
  std::memcpy(buf, report.c_str(), report.size());
  buf[report.size()] = 0;
  return count;
}

// ----------------------------------------------------------------- timeline
void* hvd_timeline_open(const char* path) {
  auto* t = new TimelineW();
  t->f = std::fopen(path, "w");
  if (!t->f) {
    delete t;
    return nullptr;
  }
  t->t0 = now_us();
  std::fputs("{\"displayTimeUnit\":\"ms\",\"traceEvents\":[", t->f);
  return t;
}

static std::string json_escape(const char* s) {
  std::string out;
  for (const char* p = s; *p; p++) {
    unsigned char ch = (unsigned char)*p;
    switch (ch) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\t': out += "\\t"; break;
      case '\r': out += "\\r"; break;
      default:
        if (ch < 0x20) {
          char esc[8];
          std::snprintf(esc, sizeof esc, "\\u%04x", ch);
          out += esc;
        } else {
          out += (char)ch;
        }
    }
  }
  return out;
}

// Append one event. ph is a single chrome-trace phase char ('X' complete,
// 'i' instant). args_json, when non-null/non-empty, must be a valid JSON
// object (the Python layer serializes it; only name/cat are escaped here).
void hvd_timeline_event(void* h, const char* name, const char* cat, char ph,
                        double ts_us, double dur_us, int pid, int tid,
                        const char* args_json) {
  auto* t = static_cast<TimelineW*>(h);
  if (!t || !t->f || !name || !cat) return;
  std::lock_guard<std::mutex> g(t->mu);
  if (!t->first) std::fputc(',', t->f);
  t->first = false;
  std::fprintf(t->f, "{\"name\":\"%s\",\"cat\":\"%s\",\"ph\":\"%c\",\"ts\":%.3f",
               json_escape(name).c_str(), json_escape(cat).c_str(), ph, ts_us);
  if (ph == 'X') std::fprintf(t->f, ",\"dur\":%.3f", dur_us);
  if (ph == 'i') std::fputs(",\"s\":\"g\"", t->f);
  std::fprintf(t->f, ",\"pid\":%d,\"tid\":%d", pid, tid);
  if (args_json && args_json[0]) std::fprintf(t->f, ",\"args\":%s", args_json);
  std::fputc('}', t->f);
}

double hvd_timeline_now_us(void* h) {
  auto* t = static_cast<TimelineW*>(h);
  return t ? now_us() - t->t0 : 0.0;
}

void hvd_timeline_close(void* h) {
  auto* t = static_cast<TimelineW*>(h);
  if (!t) return;
  {
    std::lock_guard<std::mutex> g(t->mu);
    if (t->f) {
      std::fputs("]}", t->f);
      std::fclose(t->f);
      t->f = nullptr;
    }
  }
  delete t;
}

}  // extern "C"
