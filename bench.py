"""Headline benchmark: ResNet-50 training throughput, images/sec/chip
(SURVEY §6; reference config "ResNet-50 ImageNet, examples/pytorch +
DistributedOptimizer").

Synthetic ImageNet-shaped data (no dataset in the image), bf16 compute,
SGD+momentum, full fwd+bwd+allreduce+update step through
hvd.DistributedOptimizer — the same path a user would run.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline divides by 600 img/s/chip — a typical Horovod ResNet-50 fp16
V100 figure from the reference's own benchmark suite docs.
"""

import json
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd

BASELINE_IMG_PER_SEC = 600.0


def main():
    hvd.init()
    from horovod_tpu.models import ResNet50
    backend = jax.default_backend()
    # Batch sized for one v5e chip in bf16; tiny on CPU so smoke runs finish.
    batch = 128 if backend != "cpu" else 8
    size = 224 if backend != "cpu" else 64
    steps = 20 if backend != "cpu" else 3

    model = ResNet50(num_classes=1000)
    rng = jax.random.PRNGKey(0)
    images = jnp.asarray(
        np.random.default_rng(0).standard_normal((batch, size, size, 3)),
        jnp.bfloat16)
    labels = jnp.asarray(
        np.random.default_rng(1).integers(0, 1000, (batch,)), jnp.int32)
    variables = model.init(rng, images, train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    opt = hvd.DistributedOptimizer(optax.sgd(0.1, momentum=0.9))
    opt_state = opt.init(params)

    def loss_fn(params, batch_stats, images, labels):
        logits, updates = model.apply(
            {"params": params, "batch_stats": batch_stats}, images,
            train=True, mutable=["batch_stats"])
        logp = jax.nn.log_softmax(logits)
        loss = -jnp.mean(jnp.take_along_axis(logp, labels[:, None], 1))
        return loss, updates["batch_stats"]

    # Donating params/batch_stats/opt_state lets XLA update them in place,
    # halving HBM traffic for the weight tensors on the update path.
    @partial(jax.jit, donate_argnums=(0, 1, 2))
    def train_step(params, batch_stats, opt_state, images, labels):
        (loss, batch_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch_stats, images, labels)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, batch_stats, opt_state, loss

    # Warmup (compile) then timed steps. Synchronize with a host fetch of the
    # final loss (not just block_until_ready): the chained params dependency
    # forces every step to have executed before the fetch returns, and a D2H
    # fetch is reliable across PJRT transports.
    for _ in range(3):
        params, batch_stats, opt_state, loss = train_step(
            params, batch_stats, opt_state, images, labels)
    float(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        params, batch_stats, opt_state, loss = train_step(
            params, batch_stats, opt_state, images, labels)
    float(loss)
    dt = time.perf_counter() - t0

    img_per_sec = batch * steps / dt
    print(json.dumps({
        "metric": "resnet50_images_per_sec_per_chip",
        "value": round(img_per_sec, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_per_sec / BASELINE_IMG_PER_SEC, 3),
    }))


if __name__ == "__main__":
    sys.exit(main())
