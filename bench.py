"""Benchmarks for the five reference configs (BASELINE.json):

    python bench.py                    # headline: ResNet-50, ONE JSON line
    python bench.py --model gpt2       # GPT-2 medium, tokens/s + MFU
    python bench.py --model all        # every config (headline printed last)

Each line reports throughput, step time, and TWO utilization numbers
(VERDICT r4 "what's weak" #1 — they diverge under rematerialization):

  hfu — hardware FLOPs utilization: executed TFLOP/s over peak bf16
        TFLOP/s, where executed FLOPs come from XLA's compiled-program
        cost analysis (fwd+bwd+update, FMA = 2 FLOPs). Counts remat
        RECOMPUTE, so it measures how busy the MXU is, not how much
        useful model compute it delivers.
  mfu — model FLOPs utilization: analytic, remat-invariant model FLOPs
        over the same peak. For transformer LMs the PaLM-appendix-B
        convention: 6 FLOPs per matmul parameter per token (fwd+bwd)
        plus 12·L·T·d attention FLOPs (QK^T and AV, no causal
        discount); embedding lookups are free, tied heads count once.
        For the vision configs (which run without remat) executed ==
        model FLOPs and mfu == hfu by construction.

Configs should be compared on tokens/sec and mfu; hfu explains where the
step time went (a remat config trades hfu for memory).

vs_baseline for the headline divides by 600 img/s/chip — a typical Horovod
ResNet-50/V100 fp16 figure from the reference's own benchmark suite docs.
All models run the full user path: fwd + bwd + hvd.DistributedOptimizer
update under one jit with donated state.
"""

import argparse
import json
import math
import os
import subprocess
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd

BASELINE_IMG_PER_SEC = 600.0

def _peak_tflops():
    # Device peaks (and the whole r5 MFU/HFU relabel) live in exactly one
    # place now: horovod_tpu.profiler. Kept as a module function so tests
    # can monkeypatch the peak.
    from horovod_tpu import profiler
    return profiler.peak_tflops()


def _sync(x):
    """Host fetch (block_until_ready is unreliable over some PJRT
    transports); the device queue serializes programs, so fetching the last
    result bounds them all. Slice ON DEVICE first so only one scalar
    crosses the transport — a full-leaf device_get would land inside the
    timed window and deflate every reported throughput."""
    np.asarray(jax.device_get(jax.tree_util.tree_leaves(x)[0].ravel()[:1]))


def _measure(step, state, extra, steps, program="bench_step",
             model_flops=None):
    """Register the step's compiled cost analysis in the profiler's
    program registry (flops/bytes/peak-HBM — the numbers every report
    field below derives from), then time the jitted step. Returns
    ``(dt, ProgramRecord)``; the timing also feeds the live
    ``program_mfu``/``program_hfu`` gauges via ``observe_step``."""
    from horovod_tpu import profiler
    compiled = step.lower(*state, *extra).compile()
    rec = profiler.record_cost(program, compiled, model_flops=model_flops)

    # Time through the SAME compiled executable the cost came from — the
    # AOT compile doesn't populate jit's cache, so calling `step` here
    # would compile the program a second time.
    state = compiled(*state, *extra)      # warm
    state = compiled(*state, *extra)
    _sync(state)
    t0 = time.perf_counter()
    for _ in range(steps):
        state = compiled(*state, *extra)
    _sync(state)
    dt = (time.perf_counter() - t0) / steps
    profiler.observe_step(program, dt)
    return dt, rec


def _n_params(tree):
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(tree))


def _lm_model_flops(n_matmul_params, n_layers, seq_len, d_attn, n_tokens):
    """Analytic model FLOPs for one fwd+bwd step over ``n_tokens`` tokens.

    PaLM Appendix-B accounting: each matmul parameter costs 2 FLOPs/token
    forward and 4 backward (6 total); attention adds 12·L·T·d_attn per
    token (QK^T + AV, forward 4·L·T·d, backward 2x). No causal discount —
    the standard convention, so numbers are comparable with public MFU
    tables. Remat-invariant by construction.
    """
    per_token = 6.0 * n_matmul_params + 12.0 * n_layers * seq_len * d_attn
    return per_token * n_tokens


def _collective_counters():
    """Collective-level observability embedded in every BENCH_*.json line:
    the active allreduce algorithm knob, negotiation round counts (full
    vs cached fast path) plus per-kind eager call/byte counters from the
    metrics registry. Cumulative over the process — diff consecutive
    lines of an `--model all` run to attribute counts to one config."""
    try:
        from horovod_tpu.collective import negotiation_stats
        from horovod_tpu.config import get_config
        from horovod_tpu.metrics import collective_summary, snapshot
        cfg = get_config()
        # Cumulative wire bytes the compiled allreduce buckets put on the
        # interconnect per ring traversal (trace-time counter, summed over
        # algorithm x wire labels) — the number the quantized formats cut.
        snap = snapshot()
        wire_bytes = sum(
            float(c.get("value", 0)) for c in
            snap.get("counters", {}).get("allreduce_wire_bytes_total", []))
        # Per-phase split of the same counter (the multi-leg 2D/swing
        # lowerings label each RS/AG leg separately; psum is phase-less).
        wire_bytes_by_phase = {}
        for c in snap.get("counters", {}).get(
                "allreduce_wire_bytes_total", []):
            ph = c.get("labels", {}).get("phase")
            if ph:
                wire_bytes_by_phase[ph] = (wire_bytes_by_phase.get(ph, 0)
                                           + int(c.get("value", 0)))
        from horovod_tpu import core as _core
        from horovod_tpu.overlap import parse_algorithm
        wire = (parse_algorithm(cfg.allreduce_algorithm)[1]
                or cfg.allreduce_wire)
        topo = (_core.topology_str() if _core.is_initialized()
                else (cfg.topology or ""))
        mesh = (_core.mesh_spec() if _core.is_initialized()
                else (cfg.mesh or ""))
        return {"allreduce_alg": cfg.allreduce_algorithm,
                "wire": wire,
                "topology": topo,
                "mesh": mesh,
                "overlap_chunks": cfg.overlap_chunks,
                "allreduce_wire_bytes": int(wire_bytes),
                "allreduce_wire_bytes_by_phase": wire_bytes_by_phase,
                "negotiation": negotiation_stats(),
                "collectives": collective_summary()}
    except Exception:
        return {}


def _report(metric, unit, per_sec, dt, flops, vs_baseline=None,
            model_flops=None, peak_hbm_bytes=None):
    """``flops`` is executed (XLA cost analysis) -> hfu; ``model_flops``
    is the analytic remat-invariant count -> mfu. When model_flops is
    None (vision configs, no remat) the two coincide. The split itself
    lives in ``profiler.utilization`` — bench only formats the line."""
    from horovod_tpu import profiler
    u = profiler.utilization(flops, dt, model_flops, peak=_peak_tflops())
    rec = {
        "metric": metric,
        "value": round(per_sec, 2),
        "unit": unit,
        "vs_baseline": (round(vs_baseline, 3) if vs_baseline is not None
                        else None),
        "step_ms": round(dt * 1e3, 2),
        "achieved_tflops": round(u["achieved_tflops"], 1),
        "model_tflops": round(u["model_tflops"], 1),
    }
    if peak_hbm_bytes is not None:
        rec["peak_hbm_bytes"] = int(peak_hbm_bytes)
    if u["hfu"] is not None:
        rec["hfu"] = round(u["hfu"], 3)
        rec["mfu"] = round(u["mfu"], 3)
    rec.update(_collective_counters())
    print(json.dumps(rec), flush=True)
    return rec


def bench_resnet50(on_tpu):
    from horovod_tpu.models import ResNet50
    batch, size, steps = (128, 224, 30) if on_tpu else (8, 64, 3)
    # ROOFLINE BN-ceiling experiments, CPU-prepped and flag-gated so they
    # can be measured the moment the relay answers (VERDICT r3 item 6):
    #   HOROVOD_BENCH_BN_STATS=bf16  -> bf16 BN moment accumulation
    #   HOROVOD_BENCH_STEM=s2d       -> MLPerf space-to-depth stem
    variant = {}
    bn_stats = os.environ.get("HOROVOD_BENCH_BN_STATS", "").lower()
    if bn_stats in ("bf16", "bfloat16"):
        variant["bn_stats_dtype"] = jnp.bfloat16
    elif bn_stats in ("fp32", "float32"):
        variant["bn_stats_dtype"] = jnp.float32
    stem = os.environ.get("HOROVOD_BENCH_STEM", "").lower()
    if stem:
        variant["stem"] = stem
    model = ResNet50(num_classes=1000, **variant)
    if variant:
        print(f"# resnet50 variant: {variant}", file=sys.stderr, flush=True)
    images = jnp.asarray(
        np.random.default_rng(0).standard_normal((batch, size, size, 3)),
        jnp.bfloat16)
    labels = jnp.asarray(
        np.random.default_rng(1).integers(0, 1000, (batch,)), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), images, train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]
    opt = hvd.DistributedOptimizer(optax.sgd(0.1, momentum=0.9))
    opt_state = opt.init(params)

    def loss_fn(params, batch_stats, images, labels):
        logits, updates = model.apply(
            {"params": params, "batch_stats": batch_stats}, images,
            train=True, mutable=["batch_stats"])
        logp = jax.nn.log_softmax(logits)
        loss = -jnp.mean(jnp.take_along_axis(logp, labels[:, None], 1))
        return loss, updates["batch_stats"]

    @partial(jax.jit, donate_argnums=(0, 1, 2))
    def step(params, batch_stats, opt_state, images, labels):
        (_, batch_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch_stats, images, labels)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), batch_stats, opt_state

    dt, rec = _measure(step, (params, batch_stats, opt_state),
                       (images, labels), steps, program="bench:resnet50")
    return _report("resnet50_images_per_sec_per_chip", "images/sec/chip",
                   batch / dt, dt, rec.flops,
                   vs_baseline=batch / dt / BASELINE_IMG_PER_SEC,
                   peak_hbm_bytes=rec.peak_hbm_bytes)


def _bench_lm(params, tokens, loss_fn, steps, metric, model_flops=None):
    """loss_fn closes over its token batch (synthetic data is constant
    across steps); only the train state threads through the jit."""
    opt = hvd.DistributedOptimizer(optax.adamw(1e-4))
    opt_state = opt.init(params)

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state):
        _, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    dt, rec = _measure(step, (params, opt_state), (), steps,
                       program=f"bench:{metric}", model_flops=model_flops)
    n_tokens = tokens.shape[0] * tokens.shape[1]
    return _report(metric, "tokens/sec/chip", n_tokens / dt, dt, rec.flops,
                   model_flops=rec.model_flops,
                   peak_hbm_bytes=rec.peak_hbm_bytes)


def bench_gpt2(on_tpu):
    from horovod_tpu.models.gpt2 import GPT2, GPT2Config, loss_fn
    if on_tpu:
        import dataclasses
        # HOROVOD_BENCH_REMAT=full -> full block remat; the default is the
        # selective "dots" policy (save MXU outputs, recompute elementwise
        # only), measured +19 % tokens/sec on-chip (ROOFLINE round-4 second
        # heal) and fits bs8 HBM.
        cfg = dataclasses.replace(
            GPT2Config.medium(), attention="flash", remat=True,
            remat_policy=os.environ.get("HOROVOD_BENCH_REMAT", "dots"))
        B, T, steps = 8, 1024, 10
    else:
        cfg = GPT2Config.tiny()
        B, T, steps = 2, 64, 3
    model = GPT2(cfg)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (B, T)),
        jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    # wpe is the only lookup-only table (wte counts once: the lookup is
    # free, the tied logits matmul is not).
    mflops = _lm_model_flops(
        _n_params(params) - cfg.max_seq_len * cfg.d_model,
        cfg.num_layers, T, cfg.d_model, B * T)
    return _bench_lm(
        params, tokens,
        lambda p: loss_fn(model.apply({"params": p}, tokens), tokens),
        steps, "gpt2_medium_tokens_per_sec_per_chip", model_flops=mflops)


def bench_bert(on_tpu):
    from horovod_tpu.models.bert import Bert, BertConfig, mlm_loss
    if on_tpu:
        import dataclasses
        cfg = dataclasses.replace(
            BertConfig.large(), attention="flash", remat=True,
            remat_policy=os.environ.get("HOROVOD_BENCH_REMAT", "full"))
        B, T, steps = 8, 512, 10
    else:
        cfg = BertConfig.tiny()
        B, T, steps = 2, 64, 3
    model = Bert(cfg)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, T)), jnp.int32)
    mask_pos = jnp.asarray(rng.random((B, T)) < 0.15, jnp.float32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]

    def loss(p):
        mlm, _ = model.apply({"params": p}, tokens)
        return mlm_loss(mlm, tokens, mask_pos)

    # Lookup-only tables: wpe + token-type wtt (wte is tied: lookup free,
    # mlm-head matmul counted once). Bidirectional attention => full-T
    # attention FLOPs are exact here, not a convention.
    mflops = _lm_model_flops(
        _n_params(params)
        - (cfg.max_seq_len + cfg.type_vocab_size) * cfg.d_model,
        cfg.num_layers, T, cfg.d_model, B * T)
    return _bench_lm(params, tokens, loss, steps,
                     "bert_large_tokens_per_sec_per_chip",
                     model_flops=mflops)


def bench_vit(on_tpu):
    from horovod_tpu.models.vit import ViT, ViTConfig
    cfg = ViTConfig.b16() if on_tpu else ViTConfig.tiny()
    batch, steps = (128, 20) if on_tpu else (8, 3)
    model = ViT(cfg)
    size = cfg.image_size
    images = jnp.asarray(
        np.random.default_rng(0).standard_normal((batch, size, size, 3)),
        jnp.bfloat16)
    labels = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg.num_classes, (batch,)),
        jnp.int32)
    params = model.init(jax.random.PRNGKey(0), images, train=True)["params"]
    opt = hvd.DistributedOptimizer(optax.adamw(1e-4))
    opt_state = opt.init(params)

    def loss_fn(p):
        logits = model.apply({"params": p}, images, train=True)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], 1))

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state):
        _, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    dt, rec = _measure(step, (params, opt_state), (), steps,
                       program="bench:vit")
    return _report("vit_b16_images_per_sec_per_chip", "images/sec/chip",
                   batch / dt, dt, rec.flops,
                   peak_hbm_bytes=rec.peak_hbm_bytes)


def bench_mnist(on_tpu):
    from horovod_tpu.models import MnistCNN
    batch, steps = (512, 30) if on_tpu else (64, 3)
    model = MnistCNN()
    images = jnp.asarray(
        np.random.default_rng(0).standard_normal((batch, 28, 28, 1)),
        jnp.float32)
    labels = jnp.asarray(
        np.random.default_rng(1).integers(0, 10, (batch,)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), images)["params"]
    opt = hvd.DistributedOptimizer(optax.sgd(0.1, momentum=0.9))
    opt_state = opt.init(params)

    def loss_fn(p):
        logits = model.apply({"params": p}, images,
                             rngs={"dropout": jax.random.PRNGKey(1)})
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], 1))

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state):
        _, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    dt, rec = _measure(step, (params, opt_state), (), steps,
                       program="bench:mnist")
    return _report("mnist_images_per_sec_per_chip", "images/sec/chip",
                   batch / dt, dt, rec.flops,
                   peak_hbm_bytes=rec.peak_hbm_bytes)


def _bench_torus(n):
    """Torus dims for an n-device bench ring: the HOROVOD_TOPOLOGY
    override when it factors exactly this n (the sweep shrinks n below
    the full world, where the override no longer applies), else the
    most-square factorization — the shape a real slice's detected mesh
    would approximate."""
    spec = os.environ.get("HOROVOD_TOPOLOGY")
    if spec:
        from horovod_tpu.parallel.mesh import parse_topology
        try:
            dims = parse_topology(spec)
            if int(np.prod(dims)) == n:
                return dims
        except ValueError:
            pass
    for d in range(int(math.isqrt(n)), 1, -1):
        if n % d == 0:
            return (d, n // d)
    return (n,)


def bench_allreduce(on_tpu):
    """Allreduce scaling (BASELINE's "8->256 chip scaling efficiency"
    row, measured on whatever mesh this host exposes — a virtual-CPU ICI
    proxy under the test harness, the real fabric on a multi-chip slice).

    For each device count n we time a jitted shard_map psum over the first
    n devices with a device-resident 64 MB payload and report ring bus
    bandwidth busbw = 2(n-1)/n * bytes/t; scaling efficiency is
    busbw(n) / busbw(n_min) — the fraction of per-link bandwidth kept as
    the ring grows (the metric NCCL tests report)."""
    from functools import partial as _partial

    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from horovod_tpu.config import get_config
    cfg = get_config()
    alg = cfg.allreduce_algorithm

    devs = jax.devices()
    counts = [n for n in (2, 4, 8, 16, 32, 64, 128, 256)
              if n <= len(devs)]
    payload_bytes = 64 * 1024 * 1024 if on_tpu else 8 * 1024 * 1024
    per_dev = payload_bytes // 4
    steps = 20 if on_tpu else 5
    detail = {}
    busbw0 = None
    for n in counts:
        mesh = Mesh(np.asarray(devs[:n], dtype=object), ("x",))
        sharding = NamedSharding(mesh, P("x"))
        one_row = np.ones((1, per_dev), np.float32)   # one shard of host RAM
        x = jax.make_array_from_callback((n, per_dev), sharding,
                                         lambda idx: one_row)

        from horovod_tpu.utils.compat import shard_map as _compat_shard_map

        @jax.jit
        @_partial(_compat_shard_map, mesh=mesh, in_specs=P("x"),
                  out_specs=P("x"))
        def psum_fn(v, n=n):
            # Honors HOROVOD_ALLREDUCE_ALGORITHM / --allreduce-alg, so
            # --sweep-comm measures the real per-algorithm lowering here
            # (including the quantized int8/fp8 wires and the topology-
            # aware 2D/swing schedules).
            if alg in ("psum", "auto"):
                return jax.lax.psum(v, "x")
            from horovod_tpu import overlap as _overlap
            base, qwire = _overlap.parse_algorithm(alg)
            if base == "swing":
                # every measured n is a power of two (counts above)
                return _overlap.swing_psum(v.ravel(), "x",
                                           n).reshape(v.shape)
            if base.endswith("_2d"):
                chunks = (cfg.overlap_chunks
                          if base == "chunked_rs_ag_2d" else 1)
                return _overlap.chunked_rs_ag_2d_psum(
                    v.ravel(), "x", n, dims=_bench_torus(n),
                    chunks=chunks, wire=qwire).reshape(v.shape)
            chunks = cfg.overlap_chunks if base == "chunked_rs_ag" else 1
            return _overlap.chunked_rs_ag_psum(
                v.ravel(), "x", n, chunks=chunks,
                wire=qwire).reshape(v.shape)

        _sync(psum_fn(x))                       # compile + warm
        t0 = time.perf_counter()
        for _ in range(steps):
            out = psum_fn(x)
        _sync(out)
        dt = (time.perf_counter() - t0) / steps
        busbw = 2 * (n - 1) / n * payload_bytes / dt / 1e9
        if busbw0 is None:
            busbw0 = busbw
        detail[str(n)] = {"busbw_gbps": round(busbw, 2),
                          "efficiency": round(busbw / busbw0, 3)}
    if not counts:                              # single chip: nothing to ring
        print(json.dumps({
            "metric": "allreduce_scaling_efficiency", "value": 1.0,
            "unit": "fraction", "vs_baseline": None,
            "note": "single-device mesh; scaling requires >=2 devices"}),
            flush=True)
        return
    eff = detail[str(counts[-1])]["efficiency"]
    rec = {
        "metric": "allreduce_scaling_efficiency", "value": eff,
        "unit": f"fraction_busbw_{counts[0]}to{counts[-1]}dev",
        "vs_baseline": round(eff / 0.90, 3),    # BASELINE target: >=0.90
        "payload_mb": payload_bytes // (1024 * 1024),
        "proxy": jax.default_backend() == "cpu",
        "detail": detail,
    }
    rec.update(_collective_counters())
    # This bench drives overlap.chunked_rs_ag_psum directly (no fused
    # allreduce buckets), so compute the per-traversal wire bytes of the
    # measured payload here instead of reading the bucket counter. The
    # bench lowering only quantizes when the ALGORITHM names a wire —
    # the config wire knob does not apply to it, so exact algorithms
    # are stamped fp32 whatever HOROVOD_ALLREDUCE_WIRE says.
    from horovod_tpu import overlap as _overlap
    base, qwire = _overlap.parse_algorithm(alg)
    wire = qwire or "fp32"
    n_max = counts[-1]
    dims = _bench_torus(n_max) if base.endswith("_2d") else None
    phases = _overlap.wire_bytes_by_phase(base, payload_bytes // 4, wire,
                                          n_max, dims=dims)
    rec["wire"] = wire
    rec["topology"] = "x".join(str(d) for d in (dims or (n_max,)))
    rec["allreduce_wire_bytes"] = sum(phases.values())
    rec["allreduce_wire_bytes_by_phase"] = phases
    print(json.dumps(rec), flush=True)
    return rec


def bench_gpt2_long(on_tpu):
    """Long-context single-chip config: GPT-2 medium at 4096 tokens
    (flash + selective remat — dense attention at this length would
    materialise a 16M-score tensor per head). The long-sequence regime is
    the reference fork's north star; this is its single-chip anchor
    (multi-chip sp scales it further via ring/ulysses)."""
    from horovod_tpu.models.gpt2 import GPT2, GPT2Config, loss_fn
    if on_tpu:
        import dataclasses
        cfg = dataclasses.replace(
            GPT2Config.medium(), max_seq_len=4096, attention="flash",
            remat=True,
            remat_policy=os.environ.get("HOROVOD_BENCH_REMAT", "dots"))
        B, T, steps = 2, 4096, 10
    else:
        cfg = GPT2Config.tiny()
        B, T, steps = 1, 64, 3
    model = GPT2(cfg)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (B, T)),
        jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    mflops = _lm_model_flops(
        _n_params(params) - cfg.max_seq_len * cfg.d_model,
        cfg.num_layers, T, cfg.d_model, B * T)
    return _bench_lm(
        params, tokens,
        lambda p: loss_fn(model.apply({"params": p}, tokens), tokens),
        steps, "gpt2_medium_4k_tokens_per_sec_per_chip",
        model_flops=mflops)


def bench_llama(on_tpu):
    """Llama-family config (GQA + RoPE + SwiGLU + RMSNorm): a ~340M
    Llama-shaped decoder at 2048 tokens, flash attention, selective remat.
    The flagship model family of the long-context fork needs its own perf
    anchor (VERDICT r4 item 2); 7B does not fit one v5e chip's HBM for
    training, so this is the largest round-number config that trains
    comfortably at B=4 (params+AdamW fp32 ~4 GB, dots-remat activations
    ~4.3 GB)."""
    from horovod_tpu.models.llama import Llama, LlamaConfig, loss_fn
    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=32000, max_seq_len=2048, num_layers=24,
            num_heads=16, num_kv_heads=4, d_model=1024, d_ff=2816,
            attention="flash", remat=True,
            remat_policy=os.environ.get("HOROVOD_BENCH_REMAT", "dots"))
        B, T, steps = 4, 2048, 10
    else:
        cfg = LlamaConfig.tiny()
        B, T, steps = 2, 64, 3
    model = Llama(cfg)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (B, T)),
        jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    # Only the embedding table is lookup-only (untied lm_head is a real
    # matmul). GQA expands K/V to the query head count before attention,
    # so attention FLOPs use full d_model.
    mflops = _lm_model_flops(
        _n_params(params) - cfg.vocab_size * cfg.d_model,
        cfg.num_layers, T, cfg.d_model, B * T)
    return _bench_lm(
        params, tokens,
        lambda p: loss_fn(model.apply({"params": p}, tokens), tokens),
        steps, "llama_340m_gqa_tokens_per_sec_per_chip",
        model_flops=mflops)


def bench_gpt2_packed(on_tpu):
    """Sequence-packed GPT-2 medium: the same compute shape as
    ``bench_gpt2`` but every row carries several documents with segment
    ids threading through the pallas flash kernel, packed positions, and
    the packed loss. Measures the packing-machinery tax vs plain rows —
    the number long-context users ask first."""
    from horovod_tpu.models.gpt2 import GPT2, GPT2Config, loss_fn
    from horovod_tpu.ops.attention import packed_positions
    if on_tpu:
        import dataclasses
        cfg = dataclasses.replace(
            GPT2Config.medium(), attention="flash", remat=True,
            remat_policy=os.environ.get("HOROVOD_BENCH_REMAT", "dots"))
        B, T, steps = 8, 1024, 10
    else:
        cfg = GPT2Config.tiny()
        B, T, steps = 2, 64, 3
    model = GPT2(cfg)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, T)), jnp.int32)
    # ~4 documents per row: fixed boundaries keep shapes static and the
    # workload reproducible; real pipelines vary them per batch.
    bounds = np.sort(rng.integers(T // 8, T - T // 8, (B, 3)), axis=1)
    seg = np.zeros((B, T), np.int32)
    for b in range(B):
        for cut in bounds[b]:
            seg[b, cut:] += 1
    seg = jnp.asarray(seg)
    pos = packed_positions(seg)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    mflops = _lm_model_flops(
        _n_params(params) - cfg.max_seq_len * cfg.d_model,
        cfg.num_layers, T, cfg.d_model, B * T)
    return _bench_lm(
        params, tokens,
        lambda p: loss_fn(
            model.apply({"params": p}, tokens, segment_ids=seg,
                        positions=pos),
            tokens, segment_ids=seg),
        steps, "gpt2_medium_packed_tokens_per_sec_per_chip",
        model_flops=mflops)


def bench_t5(on_tpu):
    """T5-small-class encoder-decoder at 512/512: the zoo's third
    architecture family gets its own perf anchor (dense attention by
    construction — the per-head relative-position bias is inexpressible
    in the flash kernel's per-key fused bias)."""
    from horovod_tpu.models.t5 import (T5, T5Config, seq2seq_loss,
                                       shift_right)
    if on_tpu:
        import dataclasses
        cfg = dataclasses.replace(
            T5Config.small(), remat=True,
            remat_policy=os.environ.get("HOROVOD_BENCH_REMAT", "dots"))
        B, T, steps = 16, 512, 10
    else:
        cfg = T5Config.tiny()
        B, T, steps = 2, 32, 3
    model = T5(cfg)
    rng = np.random.default_rng(0)
    src = jnp.asarray(rng.integers(1, cfg.vocab_size, (B, T)), jnp.int32)
    tgt = jnp.asarray(rng.integers(1, cfg.vocab_size, (B, T)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), src,
                        shift_right(tgt, cfg.pad_id))["params"]
    # Analytic model FLOPs: all params are matmul weights except the
    # lookup-only embedding table (lm_head is untied and real);
    # attention = enc self (bidir, T_enc) + dec self (causal, T_dec) +
    # cross (T_enc keys), each 12*L*T_kv*(H*hd) per query token.
    d_attn = cfg.num_heads * cfg.head_dim
    attn = 12.0 * (cfg.num_encoder_layers * T          # enc self
                   + cfg.num_decoder_layers * T * 2)   # dec self + cross
    mflops = (6.0 * (_n_params(params)
                     - cfg.vocab_size * cfg.d_model)
              + attn * d_attn) * B * T
    return _bench_lm(
        params, tgt,
        lambda p: seq2seq_loss(model, p, src, tgt),
        steps, "t5_small_tokens_per_sec_per_chip", model_flops=mflops)


def bench_gpt2_decode(on_tpu):
    """Inference anchor: greedy KV-cache decode throughput for GPT-2
    medium (models/generate.py — one compiled lax.scan, batch 8,
    32-token prompt, 480 generated). Decode is memory-bandwidth-bound
    (every step streams the full weights for one token per row), so
    tokens/sec here tracks HBM, not the MXU — reported without
    utilization numbers by design. Throughput counts ALL scanned decode
    steps (the prompt is teacher-forced through the same cached step, at
    identical cost), so the number is per-step honest rather than
    attributing prompt steps to generated tokens."""
    from horovod_tpu.models.generate import generate
    from horovod_tpu.models.gpt2 import GPT2, GPT2Config
    if on_tpu:
        cfg = GPT2Config.medium()
        B, P, N, reps = 8, 32, 480, 3
    else:
        cfg = GPT2Config.tiny()
        B, P, N, reps = 2, 4, 28, 1
    model = GPT2(cfg)
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(1, cfg.vocab_size, (B, P)),
        jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    if on_tpu:
        params = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16), params)

    from horovod_tpu import profiler
    # The AOT compile serves BOTH the cost capture and the bench loop —
    # routing the loop through jax.jit would compile the decode scan a
    # second time (AOT compiles don't populate jit's cache).
    fn = jax.jit(lambda p, t: generate(model, p, t, N)).lower(
        params, prompt).compile()
    prec = profiler.record_cost("bench:gpt2_decode", fn)
    _sync(fn(params, prompt))                  # warm (already compiled)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(params, prompt)
    _sync(out)
    dt = (time.perf_counter() - t0) / reps
    steps = P + N - 1                          # every scan step decodes
    # One registry "step" = one full generate() program (the compiled
    # scan), matching the cost analysis captured above.
    profiler.observe_step("bench:gpt2_decode", dt)
    rec = {
        "metric": "gpt2_medium_decode_tokens_per_sec_per_chip",
        "value": round(B * steps / dt, 2),
        "unit": "tokens/sec/chip", "vs_baseline": None,
        "step_ms": round(dt * 1e3 / steps, 3),  # per decode step
        "batch": B, "prompt": P, "new_tokens": N,
        "peak_hbm_bytes": int(prec.peak_hbm_bytes),
    }
    rec.update(_collective_counters())
    print(json.dumps(rec), flush=True)
    return rec


_BENCHES = {"resnet50": bench_resnet50, "gpt2": bench_gpt2,
            "gpt2_long": bench_gpt2_long, "llama": bench_llama,
            "gpt2_packed": bench_gpt2_packed, "t5": bench_t5,
            "gpt2_decode": bench_gpt2_decode,
            "bert": bench_bert, "vit": bench_vit, "mnist": bench_mnist,
            "allreduce": bench_allreduce}


def _apply_comm_flags(args):
    """Resolve --allreduce-alg/--overlap-chunks into the HOROVOD_* env
    (read by config.refresh() inside hvd.init()), so the bench exercises
    exactly the knob surface users set."""
    if getattr(args, "allreduce_alg", None):
        os.environ["HOROVOD_ALLREDUCE_ALGORITHM"] = args.allreduce_alg
    if getattr(args, "allreduce_wire", None):
        os.environ["HOROVOD_ALLREDUCE_WIRE"] = args.allreduce_wire
    if getattr(args, "overlap_chunks", None):
        os.environ["HOROVOD_OVERLAP_CHUNKS"] = str(args.overlap_chunks)
    if getattr(args, "topology", None):
        os.environ["HOROVOD_TOPOLOGY"] = args.topology
    if getattr(args, "mesh", None):
        os.environ["HOROVOD_MESH"] = args.mesh


#: --sweep-comm measures one line per algorithm (auto is skipped: it
#: resolves to one of the explicit lowerings per bucket size). The
#: quantized wires ride the chunked pipeline — the shape they'd resolve
#: to on real gradient buckets — and the topology-aware schedules run
#: on the _bench_torus factorization of each device count.
SWEEP_ALGS = ("psum", "rs_ag", "chunked_rs_ag",
              "chunked_rs_ag_int8", "chunked_rs_ag_fp8",
              "rs_ag_2d", "chunked_rs_ag_2d", "swing")


def _load_serve_bench():
    """tools/serve_bench.py as a module (tools/ is not a package)."""
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", "serve_bench.py")
    spec = importlib.util.spec_from_file_location("hvd_serve_bench", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def bench_serve(on_tpu):
    """--serve: Poisson-arrival serving bench (tools/serve_bench.py) —
    TTFT/TPOT/throughput percentiles under the continuous-batching
    engine. Knobs via HVD_SERVE_BENCH_{REQUESTS,RATE,SLOTS} so the CPU
    guard test stays fast without a flag zoo."""
    sb = _load_serve_bench()
    return sb.run_bench(
        model_size="medium" if on_tpu else "tiny",
        requests=int(os.environ.get(
            "HVD_SERVE_BENCH_REQUESTS", "32" if on_tpu else "10")),
        rate=float(os.environ.get("HVD_SERVE_BENCH_RATE", "25")),
        slots=int(os.environ.get(
            "HVD_SERVE_BENCH_SLOTS", "8" if on_tpu else "4")),
        max_len=256 if on_tpu else 96,
        metric="serve_tokens_per_sec_per_chip")


def _inner_main(args):
    if os.environ.get("JAX_PLATFORMS"):
        # The image's sitecustomize imports jax before env vars can apply;
        # honor an explicit platform request (e.g. the virtual CPU mesh).
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    _apply_comm_flags(args)
    hvd.init()
    on_tpu = jax.default_backend() != "cpu"
    if not on_tpu and not os.environ.get(
            "JAX_PLATFORMS", "").startswith("cpu"):
        # Nobody asked for CPU: jax fell back after a non-fatal relay
        # failure. A "successful" run here would put CPU numbers under
        # the TPU metric names — and the heal agenda would then mark the
        # config captured at this revision and never re-bench it. Refuse.
        print(json.dumps({
            "metric": _HEADLINE_METRIC.get(
                args.model, f"{args.model}_unavailable"),
            "value": None, "unit": "unavailable", "vs_baseline": None,
            "error": "backend fell back to cpu (TPU relay init failed "
                     "mid-window); refusing to record CPU numbers under "
                     "TPU metric names"}), flush=True)
        return _RC_CPU_FALLBACK
    if getattr(args, "serve", False):
        bench_serve(on_tpu)
        return
    if getattr(args, "sweep_comm", False):
        # One JSON line per allreduce algorithm for the selected model
        # (headline model when "all" was asked): hvd.init() re-reads the
        # env knob, so each pass compiles and measures the real lowering.
        model = "resnet50" if args.model == "all" else args.model
        for alg in SWEEP_ALGS:
            os.environ["HOROVOD_ALLREDUCE_ALGORITHM"] = alg
            hvd.init()
            _BENCHES[model](on_tpu)
        return
    if args.model == "all":
        # headline (resnet50) last so single-line parsers read it.
        for name in ("allreduce", "mnist", "vit", "bert", "gpt2",
                     "gpt2_long", "gpt2_packed", "llama", "t5",
                     "gpt2_decode", "resnet50"):
            _BENCHES[name](on_tpu)
    else:
        _BENCHES[args.model](on_tpu)


_HEADLINE_METRIC = {"resnet50": "resnet50_images_per_sec_per_chip",
                    "all": "resnet50_images_per_sec_per_chip",
                    "gpt2": "gpt2_medium_tokens_per_sec_per_chip",
                    "gpt2_long": "gpt2_medium_4k_tokens_per_sec_per_chip",
                    "llama": "llama_340m_gqa_tokens_per_sec_per_chip",
                    "gpt2_packed":
                        "gpt2_medium_packed_tokens_per_sec_per_chip",
                    "t5": "t5_small_tokens_per_sec_per_chip",
                    "gpt2_decode":
                        "gpt2_medium_decode_tokens_per_sec_per_chip",
                    "bert": "bert_large_tokens_per_sec_per_chip",
                    "vit": "vit_b16_images_per_sec_per_chip",
                    "mnist": "mnist_images_per_sec_per_chip",
                    "allreduce": "allreduce_scaling_efficiency"}


# Distinct child exit code for the "relay died between the probe and the
# child's init, jax fell back to cpu" refusal — the supervisor must blame
# the relay, not the code. 113 because small codes (1/2/3) are plausible
# generic crashes (ADVICE r5): any tool exiting 3 would have been
# misread as a relay death and given up with rc=0. The supervisor ALSO
# requires the child's cpu-fallback JSON record before blaming the relay
# — the exit code alone is never proof.
_RC_CPU_FALLBACK = 113


def _cpu_fallback_confirmed(stdout: str) -> bool:
    """Did the child actually print the cpu-fallback refusal record?
    Scans the child's stdout for a JSON line whose ``error`` names the
    cpu fallback — the second factor behind ``_RC_CPU_FALLBACK``."""
    for line in (stdout or "").splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if "fell back to cpu" in str(rec.get("error", "")):
            return True
    return False


def _probe_backend(timeout_s: float) -> str:
    """Check the TPU backend from a SUBPROCESS with a hard deadline.

    The relay has two failure modes (BENCH_r02: rc=1 UNAVAILABLE; and a
    wedge where ``jax.devices()`` hangs forever) — neither is recoverable
    in-process, so the probe must be a child we can kill. Returns "ok",
    "hang", or the error tail."""
    code = ("import jax\n"
            "d = jax.devices()\n"
            "print('HVD_PROBE_OK', d[0].platform, len(d))\n")
    try:
        r = subprocess.run([sys.executable, "-c", code], timeout=timeout_s,
                           capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        return "hang"
    if r.returncode == 0 and "HVD_PROBE_OK" in r.stdout:
        platform = r.stdout.split("HVD_PROBE_OK", 1)[1].split()[0]
        if platform == "cpu":
            # jax fell back to CPU after a non-fatal relay failure: a
            # "successful" run here would publish CPU numbers under the
            # TPU metric names — treat as a failed probe instead.
            return "backend fell back to cpu (TPU relay init failed)"
        return "ok"
    return (r.stderr or r.stdout).strip()[-400:] or f"rc={r.returncode}"


def _supervise(args) -> int:
    """Run the bench as a supervised child so a relay wedge yields an
    honest JSON line (value null + reason) instead of rc=1 or a silent
    hang — the driver records the last JSON line whatever happens."""
    probe_timeout = float(os.environ.get("HVD_BENCH_PROBE_TIMEOUT", "60"))
    attempts = int(os.environ.get("HVD_BENCH_PROBE_ATTEMPTS", "5"))
    backoff = float(os.environ.get("HVD_BENCH_PROBE_BACKOFF", "90"))
    # "all" is now 11 configs (llama/t5/packed/decode joined in r5),
    # several compile-heavy — give the multi-config run twice the budget
    # so a healthy-but-slow sweep isn't mislabeled a relay wedge.
    run_timeout = float(os.environ.get(
        "HVD_BENCH_RUN_TIMEOUT", "5400" if args.model == "all" else "2700"))

    def give_up(reason, note, rc=0):
        print(json.dumps({
            "metric": _HEADLINE_METRIC.get(
                args.model, f"{args.model}_unavailable"),
            "value": None, "unit": "unavailable", "vs_baseline": None,
            "error": reason, "note": note}), flush=True)
        return rc

    relay_note = ("TPU relay unreachable at bench time; see ROOFLINE.md "
                  "for the last self-measured numbers on this code.")

    last = None
    for i in range(attempts):
        if i:
            time.sleep(backoff)
        last = _probe_backend(probe_timeout)
        print(f"# probe {i + 1}/{attempts}: "
              f"{'ok' if last == 'ok' else last!r}", file=sys.stderr,
              flush=True)
        if last == "ok":
            break
    else:
        kind = "hung (relay wedge)" if last == "hang" else f"failed: {last}"
        waited = (attempts - 1) * backoff + attempts * (
            probe_timeout if last == "hang" else 0)
        return give_up(f"TPU backend probe {kind} "
                       f"x{attempts} over ~{waited / 60:.0f}min",
                       relay_note)

    # Backend answers — run the real bench with a deadline in case the
    # relay wedges mid-run.
    cmd = [sys.executable, os.path.abspath(__file__),
           "--model", args.model, "--inner"]
    if getattr(args, "allreduce_alg", None):
        cmd += ["--allreduce-alg", args.allreduce_alg]
    if getattr(args, "allreduce_wire", None):
        cmd += ["--allreduce-wire", args.allreduce_wire]
    if getattr(args, "overlap_chunks", None):
        cmd += ["--overlap-chunks", str(args.overlap_chunks)]
    if getattr(args, "topology", None):
        cmd += ["--topology", args.topology]
    if getattr(args, "mesh", None):
        cmd += ["--mesh", args.mesh]
    if getattr(args, "sweep_comm", False):
        cmd += ["--sweep-comm"]
    if getattr(args, "serve", False):
        cmd += ["--serve"]
    try:
        # Captured (not inherited) stdout: the cpu-fallback exit code is
        # only believed when the child's refusal record is actually in
        # the stream. Echoed through below — the driver still records
        # the last JSON line.
        r = subprocess.run(cmd, timeout=run_timeout, capture_output=True,
                           text=True)
    except subprocess.TimeoutExpired:
        return give_up(f"bench run exceeded {run_timeout:.0f}s "
                       f"(relay wedged mid-run)", relay_note)
    child_out = getattr(r, "stdout", None) or ""
    child_err = getattr(r, "stderr", None) or ""
    if child_out:
        sys.stdout.write(child_out)
        sys.stdout.flush()
    if child_err:
        sys.stderr.write(child_err)
        sys.stderr.flush()
    if r.returncode == _RC_CPU_FALLBACK:
        if _cpu_fallback_confirmed(child_out):
            # The child itself diagnosed a mid-window relay death (cpu
            # fallback) — that's a relay failure, not a code one.
            return give_up("TPU relay died between the probe and the "
                           "bench child's init (cpu fallback refused)",
                           relay_note)
        # The exit code without the record is some OTHER failure that
        # happened to exit 113 — a code problem, not the relay's.
        return give_up(f"bench run exited rc={r.returncode} without the "
                       "cpu-fallback record",
                       "bench child crashed after a healthy backend probe "
                       "— likely a code regression, not the relay.", rc=1)
    if r.returncode != 0:
        # The probe just proved the relay reachable, so a crashing child
        # is most likely a CODE regression — say so and keep the nonzero
        # rc so gates notice; the JSON line still carries the detail.
        return give_up(f"bench run exited rc={r.returncode} "
                       f"after a successful backend probe",
                       "bench child crashed after a healthy backend probe "
                       "— likely a code regression, not the relay.", rc=1)
    return 0


def _build_parser():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50",
                   choices=list(_BENCHES) + ["all"])
    p.add_argument("--inner", action="store_true",
                   help="run directly in-process (no probe/supervision)")
    p.add_argument("--allreduce-alg", dest="allreduce_alg", default=None,
                   choices=["auto", "psum", "rs_ag", "chunked_rs_ag",
                            "rs_ag_int8", "chunked_rs_ag_int8",
                            "rs_ag_fp8", "chunked_rs_ag_fp8",
                            "rs_ag_2d", "chunked_rs_ag_2d",
                            "rs_ag_2d_int8", "chunked_rs_ag_2d_int8",
                            "rs_ag_2d_fp8", "chunked_rs_ag_2d_fp8",
                            "swing"],
                   help="gradient-sync algorithm for this run "
                        "(HOROVOD_ALLREDUCE_ALGORITHM)")
    p.add_argument("--allreduce-wire", dest="allreduce_wire", default=None,
                   choices=["fp32", "bf16", "int8", "fp8"],
                   help="default allreduce wire precision "
                        "(HOROVOD_ALLREDUCE_WIRE)")
    p.add_argument("--overlap-chunks", dest="overlap_chunks", type=int,
                   default=None,
                   help="chunked_rs_ag pipeline depth "
                        "(HOROVOD_OVERLAP_CHUNKS)")
    p.add_argument("--topology", dest="topology", default=None,
                   help="torus-dims override like 2x4 "
                        "(HOROVOD_TOPOLOGY); must factor the world size")
    p.add_argument("--mesh", dest="mesh", default=None,
                   help="dp×mp mesh like dp2xmp4 (HOROVOD_MESH); "
                        "dp*mp must equal the world size")
    p.add_argument("--sweep-comm", dest="sweep_comm", action="store_true",
                   help="one JSON line per allreduce algorithm "
                        f"({', '.join(SWEEP_ALGS)}) for the selected "
                        "model")
    p.add_argument("--serve", dest="serve", action="store_true",
                   help="Poisson-arrival serving bench (continuous-"
                        "batching engine): TTFT/TPOT/throughput "
                        "percentiles as one JSON line")
    return p


def main():
    args = _build_parser().parse_args()
    if args.inner or os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        # Explicit CPU runs (tests, virtual mesh) never touch the relay.
        return _inner_main(args)
    return _supervise(args)


if __name__ == "__main__":
    sys.exit(main())
