"""GPT-2 trained with pipeline parallelism (GPipe schedule over a ``pp``
mesh axis): transformer blocks staged across devices, microbatches streamed
through ``ppermute`` hops, loss masked to the last stage inside
``pipeline_loss`` so gradients need no caller-side scaling.

Run (virtual 8-device CPU mesh):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/gpt2_pipeline.py --stages 8 --microbatches 8
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # Force the platform via config: env-var-only selection can still try to
    # initialize an accelerator plugin registered at interpreter startup.
    import jax
    jax.config.update("jax_platforms", "cpu")


import argparse

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models.gpt2 import GPT2, GPT2Config
from horovod_tpu.models.gpt2_pipeline import (gpt2_pp_loss_and_grad,
                                              stack_block_params)
from horovod_tpu.utils.compat import shard_map as _compat_shard_map


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--stages", type=int, default=None,
                    help="pipeline stages (default: all devices)")
    ap.add_argument("--interleave", type=int, default=0, metavar="R",
                    help="use the circular schedule with R rounds per "
                         "device (model depth = stages*R*layers-per-stage; "
                         "requires microbatches <= stages)")
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel width inside every stage "
                         "(Megatron-in-GPipe; devices = stages * tp)")
    ap.add_argument("--layers-per-stage", type=int, default=1)
    ap.add_argument("--microbatches", type=int, default=8)
    ap.add_argument("--microbatch-size", type=int, default=2)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--d-model", type=int, default=64)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--lr", type=float, default=0.1)
    args = ap.parse_args()

    hvd.init(axis_name="pp")
    TP = max(args.tp, 1)
    S = args.stages or hvd.size() // TP
    if S < 1 or S * TP > len(jax.devices()):
        raise SystemExit(
            f"--stages {S} x --tp {TP} does not fit the "
            f"{len(jax.devices())} available devices")
    if hvd.size() != S * TP:
        hvd.init(devices=jax.devices()[:S * TP], axis_name="pp")

    R = max(args.interleave, 0)
    layers = S * args.layers_per_stage * (R or 1)
    cfg = GPT2Config(vocab_size=256, max_seq_len=args.seq,
                     num_layers=layers, num_heads=4,
                     d_model=args.d_model, dtype=jnp.float32)
    M, mb, T = args.microbatches, args.microbatch_size, args.seq
    if R:
        if M > S:
            raise SystemExit(
                f"--interleave requires --microbatches ({M}) <= stages "
                f"({S}); chunk the batch and accumulate gradients instead")
        bubble = 1 - R * M / (M + R * S - 1)
        print(f"stages={S} rounds={R} layers={layers} microbatches={M} "
              f"-> bubble {bubble:.1%} (circular)")
    else:
        bubble = (S - 1) / (M + S - 1)
        print(f"stages={S} layers/stage={args.layers_per_stage} "
              f"microbatches={M} -> bubble {bubble:.1%} (GPipe)")

    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (M, mb, T)),
                         jnp.int32)
    params = GPT2(cfg).init(jax.random.PRNGKey(0),
                            tokens.reshape(M * mb, T))["params"]
    if TP > 1:
        # Megatron-in-GPipe: every stage's matmuls head/feature-split over
        # a tp mesh axis (f/g conjugate ops inside the stage body).
        from horovod_tpu.models.gpt2_pipeline import (
            block_specs_tp, gpt2_pp_tp_loss_and_grad,
            gpt2_pp_tp_loss_and_grad_interleaved, make_pp_tp_params,
            make_pp_tp_params_interleaved)
        from horovod_tpu.parallel import make_mesh
        if R:
            blocks, rest = make_pp_tp_params_interleaved(
                params, S, R, cfg.num_heads)
            grad_step = gpt2_pp_tp_loss_and_grad_interleaved(cfg, "pp",
                                                             "tp")
            specs = block_specs_tp("pp", "tp", extra_dims=1)
        else:
            blocks, rest = make_pp_tp_params(params, S, cfg.num_heads)
            grad_step = gpt2_pp_tp_loss_and_grad(cfg, "pp", "tp")
            specs = block_specs_tp("pp", "tp")

        mesh = make_mesh({"pp": S, "tp": TP},
                         devices=jax.devices()[:S * TP])
        print(f"tensor-parallel width tp={TP} inside every stage")
    elif R:
        from horovod_tpu.models.gpt2_pipeline import (
            stack_block_params_interleaved,
            gpt2_pp_loss_and_grad_interleaved)
        blocks, rest = stack_block_params_interleaved(params, S, R)
        grad_step = gpt2_pp_loss_and_grad_interleaved(cfg, axis_name="pp")
    else:
        blocks, rest = stack_block_params(params, S)
        grad_step = gpt2_pp_loss_and_grad(cfg, axis_name="pp")

    def train_step(blocks, rest, tokens):
        loss, g_blocks, g_rest = grad_step(blocks, rest, tokens)
        blocks = jax.tree_util.tree_map(
            lambda p, g: p - args.lr * g, blocks, g_blocks)
        rest = jax.tree_util.tree_map(
            lambda p, g: p - args.lr * g, rest, g_rest)
        return loss, blocks, rest

    if TP > 1:
        fn = jax.jit(_compat_shard_map(
            train_step, mesh=mesh, in_specs=(specs, P(), P()),
            out_specs=(P(), specs, P()), check_vma=False))
    else:
        fn = hvd.spmd(train_step,
                      in_specs=(P("pp"), P(), P()),
                      out_specs=(P(), P("pp"), P()))
    for step in range(args.steps):
        loss, blocks, rest = fn(blocks, rest, tokens)
        print(f"step {step}: loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
