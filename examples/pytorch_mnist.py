"""Upstream-shaped PyTorch training script (mirrors
``examples/pytorch/pytorch_mnist.py`` in the reference): the intended diff
for a migrating user is the import — ``import horovod.torch as hvd``
becomes ``import horovod_tpu.torch as hvd``. Synthetic MNIST-shaped data.

Run:  python examples/pytorch_mnist.py --steps 60
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.01)
    args = ap.parse_args()

    import torch
    import torch.nn.functional as F

    import horovod_tpu.torch as hvd
    from horovod_tpu.data import DistributedSampler

    # --- the upstream script body, unchanged in structure ------------------
    hvd.init()
    torch.manual_seed(42)

    class Net(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.conv1 = torch.nn.Conv2d(1, 10, kernel_size=5)
            self.fc1 = torch.nn.Linear(10 * 12 * 12, 50)
            self.fc2 = torch.nn.Linear(50, 10)

        def forward(self, x):
            x = F.relu(F.max_pool2d(self.conv1(x), 2))
            x = x.flatten(1)
            x = F.relu(self.fc1(x))
            return F.log_softmax(self.fc2(x), dim=1)

    model = Net()

    rng = np.random.default_rng(0)
    n = args.batch * 4
    images = torch.from_numpy(
        rng.standard_normal((n, 1, 28, 28)).astype(np.float32))
    labels = torch.from_numpy(rng.integers(0, 10, (n,)).astype(np.int64))

    # Upstream partitions with torch's DistributedSampler(rank, size);
    # same wrap-pad semantics here.
    sampler = DistributedSampler(n, rank=hvd.rank(), size=hvd.size())

    optimizer = torch.optim.SGD(model.parameters(),
                                lr=args.lr * hvd.size(), momentum=0.5)
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)
    optimizer = hvd.DistributedOptimizer(optimizer)

    first = None
    step = 0
    while step < args.steps:
        indices = list(iter(sampler))
        for idx in np.array_split(indices,
                                  max(1, len(indices) // args.batch)):
            data, target = images[idx], labels[idx]
            optimizer.zero_grad()
            output = model(data)
            loss = F.nll_loss(output, target)
            loss.backward()
            optimizer.step()    # allreduces grads, then inner step
            if first is None:
                first = float(loss)
            if step % 10 == 0:
                print(f"step {step}: loss {float(loss):.4f}")
            step += 1
            if step >= args.steps:
                break
        sampler.set_epoch(step)
    print(f"loss {first:.4f} -> {float(loss):.4f}")
    assert float(loss) < first, "training did not reduce the loss"


if __name__ == "__main__":
    main()
