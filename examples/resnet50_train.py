"""ResNet-50 data-parallel training (the headline benchmark config;
reference ``examples/pytorch/pytorch_imagenet_resnet50.py``), with
checkpointing, timeline, and the health watchdog — synthetic ImageNet shapes.
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # Force the platform via config: env-var-only selection can still try to
    # initialize an accelerator plugin registered at interpreter startup.
    import jax
    jax.config.update("jax_platforms", "cpu")


import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import timeline as tl
from horovod_tpu.callbacks import warmup_schedule
from horovod_tpu.models import ResNet50
from horovod_tpu.utils import HealthWatchdog


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-per-chip", type=int, default=64)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--checkpoint-dir", default=None)
    ap.add_argument("--timeline", default=None)
    args = ap.parse_args()

    hvd.init()
    n = hvd.size()
    if args.timeline:
        tl.init_timeline(args.timeline)

    model = ResNet50(num_classes=1000)
    B = args.batch_per_chip * n
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.standard_normal(
        (B, args.image_size, args.image_size, 3)), jnp.bfloat16)
    labels = jnp.asarray(rng.integers(0, 1000, (B,)), jnp.int32)

    variables = model.init(jax.random.PRNGKey(0), images[:2], train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    sched = warmup_schedule(0.1, warmup_epochs=5, steps_per_epoch=args.steps)
    opt = hvd.DistributedOptimizer(optax.sgd(sched, momentum=0.9),
                                   compression=hvd.Compression.bf16)
    opt_state = opt.init(params)

    def train_step(params, batch_stats, opt_state, images, labels):
        def loss_fn(p, bs):
            logits, upd = model.apply(
                {"params": p, "batch_stats": bs}, images, train=True,
                mutable=["batch_stats"])
            loss = -jnp.mean(jnp.take_along_axis(
                jax.nn.log_softmax(logits), labels[:, None], 1))
            return loss, upd["batch_stats"]

        (loss, batch_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch_stats)
        grads = hvd.allreduce_gradients(grads)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), batch_stats, \
            opt_state, loss

    step = hvd.spmd(train_step,
                    in_specs=(P(), P(), P(), P("hvd"), P("hvd")),
                    out_specs=(P(), P(), P(), P()),
                    donate_argnums=(0, 1, 2))

    with HealthWatchdog(timeout_s=300):
        t0 = time.perf_counter()
        for i in range(args.steps):
            params, batch_stats, opt_state, loss = step(
                params, batch_stats, opt_state, images, labels)
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
    print(f"{B * args.steps / dt:.1f} images/sec total "
          f"({B * args.steps / dt / n:.1f}/chip), final loss "
          f"{float(loss):.3f}")

    if args.checkpoint_dir:
        from horovod_tpu.checkpoint import save_checkpoint
        save_checkpoint(args.checkpoint_dir,
                        {"params": params, "batch_stats": batch_stats},
                        step=args.steps)
        print(f"checkpoint saved to {args.checkpoint_dir}")
    if args.timeline:
        tl.shutdown_timeline()


if __name__ == "__main__":
    main()
