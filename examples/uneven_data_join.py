"""Uneven per-rank data with the mask-based Join (upstream ``hvd.join``'s
purpose, the SPMD way): every rank runs the step loop to the MAX step
count; ranks that have exhausted their data pass ``alive=0`` so they
contribute zero gradients and the mean divides by the live count — exactly
upstream's joined-rank-contributes-nothing semantics, but inside one jitted
program (no controller, no early exit).

Run (virtual 8-device CPU mesh):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/uneven_data_join.py
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    args = ap.parse_args()

    hvd.init()
    n = hvd.size()

    # Rank r has (r+1) * 4 batches — genuinely uneven data.
    rng = np.random.default_rng(0)
    per_rank_batches = [(r + 1) * 4 for r in range(n)]
    max_steps = min(args.steps, max(per_rank_batches))
    print("batches per rank:", per_rank_batches, "running", max_steps,
          "steps")

    X = jnp.asarray(rng.standard_normal((n, max_steps, 16, 4)), jnp.float32)
    true_w = jnp.asarray([[1.0], [-2.0], [0.5], [3.0]])
    Y = jnp.einsum("rsbf,fo->rsbo", X, true_w)[..., 0] + 0.1
    limits = jnp.asarray(per_rank_batches, jnp.int32)

    W = jnp.zeros((4, 1))
    b = jnp.zeros((1,))
    # The gradient sync is the explicit masked allreduce below, so the
    # inner optimizer stays plain (DistributedOptimizer would reduce again).
    opt = optax.sgd(0.1)
    opt_state = opt.init((W, b))

    def train_step(params, opt_state, x, y, limit, step):
        W, b = params

        def loss_fn(Wb):
            W, b = Wb
            pred = x @ W + b[None]
            return jnp.mean((pred[..., 0] - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)((W, b))
        # The Join: this rank is alive while it still has data. Dead ranks
        # contribute zeros; the mean divides by the live count.
        alive = (step < limit).astype(jnp.float32)
        grads = hvd.allreduce_gradients(grads, alive=alive)
        updates, opt_state = opt.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), opt_state,
                loss[None], alive[None])

    def body(params, opt_state, X, Y, limits, step):
        return train_step(params, opt_state, X[0, step], Y[0, step],
                          limits[0], step)

    fn = hvd.spmd(body,
                  in_specs=(P(), P(), P("hvd"), P("hvd"), P("hvd"), P()),
                  out_specs=(P(), P(), P("hvd"), P("hvd")))
    params = (W, b)
    for step in range(max_steps):
        params, opt_state, loss, alive = fn(params, opt_state, X, Y, limits,
                                            jnp.int32(step))
        live = int(np.asarray(alive).sum())
        print(f"step {step:2d}: live ranks {live}/{n}  mean local loss "
              f"{float(np.asarray(loss).mean()):.4f}")
    resid = float(jnp.mean(jnp.abs(params[0] - true_w)))
    print("final |W - true|:", round(resid, 4))
    assert resid < 0.2


if __name__ == "__main__":
    main()
