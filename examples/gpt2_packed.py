"""Sequence-packed GPT-2 pretraining — many documents per row, exactly.

The standard long-context data format: variable-length documents are
packed back-to-back into fixed-length rows (no padding waste).
``segment_ids`` block attention across document boundaries on every
attention impl (the pallas flash kernels mask score tiles to same-segment
pairs), ``packed_positions`` restarts position ids per document, and
``loss_fn(..., segment_ids=)`` drops the cross-boundary targets — so
packing is EXACT: each packed document trains as if it were alone.

Run (single device or dp):
  JAX_PLATFORMS=cpu python examples/gpt2_packed.py --steps 3
Add --flash for the fused pallas kernel (interpreter-mode on CPU).
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")

import argparse
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models.gpt2 import GPT2, GPT2Config, loss_fn
from horovod_tpu.ops.attention import packed_positions


# Packing is a library utility: first-fit-decreasing row assignment
# (native C++ hvd_pack_ffd when available) + filler tokens with DISTINCT
# negative segment ids, so the packed loss drops every filler target and
# "never trains on filler" is literally true. See data/packing.py.
from horovod_tpu.data import pack_documents


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--row-len", type=int, default=128)
    ap.add_argument("--flash", action="store_true")
    args = ap.parse_args()

    hvd.init()
    cfg = dataclasses.replace(
        GPT2Config.tiny(), max_seq_len=args.row_len,
        attention="flash" if args.flash else "dense")
    model = GPT2(cfg)

    # Synthetic corpus: documents of wildly different lengths.
    rng = np.random.default_rng(0)
    docs = [rng.integers(1, cfg.vocab_size, rng.integers(8, 60)).tolist()
            for _ in range(12)]
    tokens, seg = pack_documents(docs, args.row_len)
    tokens, seg = jnp.asarray(tokens), jnp.asarray(seg)
    pos = packed_positions(seg)
    if hvd.rank() == 0:
        n_docs = int(seg.max()) + 1
        print(f"packed {n_docs} segments into {tokens.shape[0]} rows of "
              f"{args.row_len} tokens", flush=True)

    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    opt = hvd.DistributedOptimizer(optax.adamw(3e-3))
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        def loss(p):
            logits = model.apply({"params": p}, tokens,
                                 segment_ids=seg, positions=pos)
            return loss_fn(logits, tokens, segment_ids=seg)
        l, grads = jax.value_and_grad(loss)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, l

    first = last = None
    for i in range(args.steps):
        params, opt_state, l = step(params, opt_state)
        last = float(l)
        first = first if first is not None else last
        print(f"step {i}: packed loss {last:.4f}", flush=True)

    # The exactness claim, demonstrated: document 0's logits inside the
    # packed row equal running it alone (FFD may have placed it in any
    # row/offset — locate it by its segment id).
    rr, cc = np.where(np.asarray(seg) == 0)
    row, c0, c1 = int(rr[0]), int(cc.min()), int(cc.max()) + 1
    d0 = tokens[row, c0:c1][None]
    got = model.apply({"params": params}, tokens,
                      segment_ids=seg, positions=pos)[row, c0:c1]
    alone = model.apply({"params": params}, d0)[0]
    err = float(jnp.abs(got - alone).max())
    print(f"packed-vs-alone max logit diff: {err:.2e}", flush=True)
    assert err < 5e-2, err
    if args.steps > 1:
        assert last < first, "loss did not decrease"


if __name__ == "__main__":
    main()
