"""GPT-2 with dp x tp sharding (reference config "GPT-2 medium,
tensor-fusion stress"): Megatron-style partition rules + GSPMD — XLA inserts
the collectives the reference's NCCL stack would issue by hand.
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # Force the platform via config: env-var-only selection can still try to
    # initialize an accelerator plugin registered at interpreter startup.
    import jax
    jax.config.update("jax_platforms", "cpu")


import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models.gpt2 import GPT2, GPT2Config, loss_fn, partition_rules
from horovod_tpu.parallel import make_mesh, shard_pytree


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--d-model", type=int, default=128)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--steps", type=int, default=5)
    args = ap.parse_args()

    hvd.init()
    n = hvd.size()
    tp = min(args.tp, n)
    mesh = make_mesh({"dp": n // tp, "tp": tp})
    print(f"mesh: {dict(zip(mesh.axis_names, mesh.devices.shape))}")

    cfg = GPT2Config(vocab_size=512, max_seq_len=args.seq,
                     num_layers=args.layers, num_heads=4,
                     d_model=args.d_model)
    model = GPT2(cfg)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (8, args.seq)),
                         jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    params = shard_pytree(params, mesh, partition_rules())
    tokens = jax.device_put(tokens, NamedSharding(mesh, P("dp", None)))

    opt = hvd.DistributedOptimizer(optax.adamw(3e-4))
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, tokens):
        l, g = jax.value_and_grad(
            lambda p: loss_fn(model.apply({"params": p}, tokens), tokens))(
            params)
        updates, opt_state = opt.update(g, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, l

    for i in range(args.steps):
        params, opt_state, l = step(params, opt_state, tokens)
        print(f"step {i}: loss={float(l):.4f}")


if __name__ == "__main__":
    main()
