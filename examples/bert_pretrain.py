"""BERT-large MLM+NSP pretraining — mirrors the reference benchmark config
"BERT-large pretraining (TF2 DistributedGradientTape + Adasum)" on the JAX
frontend: DistributedGradientTape-style grad sync with the Adasum reduction,
flash attention, and the sharded data pipeline (synthetic corpus: no
datasets ship in the image).

Run single-host:      python examples/bert_pretrain.py
Virtual 8-dev CPU:    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
                      JAX_PLATFORMS=cpu python examples/bert_pretrain.py
"""

import dataclasses
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # Force the platform via config: env-var-only selection can still try to
    # initialize an accelerator plugin registered at interpreter startup.
    import jax
    jax.config.update("jax_platforms", "cpu")


import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.data import ShardedBatchIterator
from horovod_tpu.models.bert import Bert, BertConfig


def main(steps: int = 20, batch_per_rank: int = 4, seq_len: int = 64,
         tiny: bool = True):
    hvd.init()
    n = hvd.size()
    print(f"communicator: size={n} backend={jax.default_backend()}")

    cfg = BertConfig.tiny() if tiny else BertConfig.large()
    if jax.default_backend() == "tpu":
        cfg = dataclasses.replace(cfg, attention="flash")
    model = Bert(cfg)

    # Synthetic corpus, sharded per rank by the data pipeline.
    rng = np.random.default_rng(0)
    n_docs = steps * batch_per_rank * n
    corpus_tokens = rng.integers(4, cfg.vocab_size, (n_docs, seq_len))
    corpus_types = np.zeros_like(corpus_tokens)
    corpus_nsp = rng.integers(0, 2, (n_docs,))

    tokens0 = jnp.zeros((batch_per_rank, seq_len), jnp.int32)
    mask0 = jnp.ones((batch_per_rank, seq_len), bool)
    variables = model.init(jax.random.PRNGKey(0), tokens0, tokens0, mask0)
    params = variables["params"]

    # Adasum reduction (the reference's BERT recipe): scale-free gradient
    # combining that tolerates large effective batch sizes.
    opt = hvd.DistributedOptimizer(optax.adamw(1e-4), op=hvd.Adasum)
    opt_state = opt.init(params)

    def train_step(params, opt_state, tokens, types, nsp_labels):
        params = hvd.broadcast_parameters(params, root_rank=0)
        mask = jnp.ones_like(tokens, bool)

        def loss_fn(p):
            # MLM: replace ~1/7 of input positions with [MASK] (id 3) and
            # score the original tokens there, + NSP.
            mlm_pos = jnp.arange(tokens.shape[1]) % 7 == 0
            masked_tokens = jnp.where(mlm_pos[None], 3, tokens)
            mlm_logits, nsp_logits = model.apply(
                {"params": p}, masked_tokens, types, mask)
            logp = jax.nn.log_softmax(mlm_logits.astype(jnp.float32))
            mlm_ll = jnp.take_along_axis(logp, tokens[..., None], -1)[..., 0]
            mlm_loss = -jnp.mean(jnp.where(mlm_pos[None], mlm_ll, 0.0))
            nsp_lp = jax.nn.log_softmax(nsp_logits.astype(jnp.float32))
            nsp_loss = -jnp.mean(
                jnp.take_along_axis(nsp_lp, nsp_labels[:, None], -1))
            return mlm_loss + nsp_loss

        loss, grads = hvd.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    step = hvd.spmd(train_step,
                    in_specs=(P(), P(), P("hvd"), P("hvd"), P("hvd")),
                    out_specs=(P(), P(), P()))

    data = ShardedBatchIterator(
        [corpus_tokens, corpus_types, corpus_nsp],
        batch_size=batch_per_rank * n, rank=0, size=1, seed=0)
    for i, ((tokens, types, nsp), _mask) in enumerate(data):
        params, opt_state, loss = step(
            params, opt_state,
            jnp.asarray(tokens, jnp.int32), jnp.asarray(types, jnp.int32),
            jnp.asarray(nsp, jnp.int32))
        if i % 5 == 0:
            print(f"step {i}: loss={float(loss):.4f}")
        if i + 1 >= steps:
            break
    print(f"final loss={float(loss):.4f}")


if __name__ == "__main__":
    main()
