"""Upstream-shaped TF2 training script (mirrors
``examples/tensorflow2/tensorflow2_mnist.py`` in the reference): the only
intended change for a migrating user is the import line —
``import horovod.tensorflow as hvd`` becomes
``import horovod_tpu.tensorflow as hvd``. Synthetic MNIST-shaped data (no
dataset downloads in this image).

Run:  python examples/tensorflow2_mnist.py --steps 60
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.001)
    args = ap.parse_args()

    import tensorflow as tf

    import horovod_tpu.tensorflow as hvd

    # --- the upstream script body, unchanged in structure ------------------
    hvd.init()

    rng = np.random.default_rng(hvd.rank())
    images = rng.standard_normal(
        (args.batch * 4, 28, 28, 1)).astype(np.float32)
    labels = rng.integers(0, 10, (args.batch * 4,)).astype(np.int64)
    dataset = tf.data.Dataset.from_tensor_slices((images, labels))
    dataset = dataset.repeat().shuffle(1024).batch(args.batch)

    mnist_model = tf.keras.Sequential([
        tf.keras.layers.Conv2D(32, [3, 3], activation="relu"),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(64, activation="relu"),
        tf.keras.layers.Dense(10, activation="softmax"),
    ])
    loss_obj = tf.losses.SparseCategoricalCrossentropy()
    # Upstream scales the LR by the number of workers and synchronizes via
    # the tape alone (wrapping the optimizer too would allreduce twice).
    opt = tf.optimizers.Adam(args.lr * hvd.size())

    @tf.function
    def training_step(images, labels, first_batch):
        with tf.GradientTape() as tape:
            tape = hvd.DistributedGradientTape(tape)
            probs = mnist_model(images, training=True)
            loss_value = loss_obj(labels, probs)
        grads = tape.gradient(loss_value, mnist_model.trainable_variables)
        opt.apply_gradients(zip(grads, mnist_model.trainable_variables))
        if first_batch:
            # Upstream broadcasts initial state after the first step so the
            # optimizer slots exist.
            hvd.broadcast_variables(mnist_model.variables, root_rank=0)
            opt_vars = opt.variables() if callable(opt.variables) \
                else opt.variables
            hvd.broadcast_variables(opt_vars, root_rank=0)
        return loss_value

    first = None
    for batch_idx, (images, labels) in enumerate(
            dataset.take(args.steps)):
        loss_value = training_step(images, labels, batch_idx == 0)
        if first is None:
            first = float(loss_value)
        if batch_idx % 10 == 0:
            print(f"step {batch_idx}: loss {float(loss_value):.4f}")
    print(f"loss {first:.4f} -> {float(loss_value):.4f}")
    assert float(loss_value) < first, "training did not reduce the loss"


if __name__ == "__main__":
    main()
