"""Long-context GPT-2 with striped ring attention (sequence parallelism).

The north-star long-context recipe (SURVEY §2 row 24) end-to-end: a
sequence far beyond one device's attention budget is sharded over the
``sp`` mesh axis in the **striped** layout (shard r holds global positions
r, r+n, r+2n, ... — Striped Attention), attention runs as a ring of
per-block computations with K/V hopping shard-to-shard via ``ppermute``,
and the loss is ``striped_lm_loss`` — exact over every next-token pair,
including the shard boundaries a contiguous per-shard shift would drop.

Run (8 virtual devices, T_global = 2048):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/gpt2_long_context.py --steps 3
On a TPU slice the same script rides ICI; add --flash for the pallas
flash kernel per ring block (interpreter-mode on CPU: slow but exact).
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")

import argparse


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--seq-len", type=int, default=2048,
                    help="GLOBAL sequence length (sharded over sp)")
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--flash", action="store_true",
                    help="pallas flash kernel per ring block")
    ap.add_argument("--lr", type=float, default=1e-3)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.models.gpt2 import GPT2, GPT2Config, striped_lm_loss

    hvd.init(axis_name="sp")
    n = hvd.size()
    T = args.seq_len
    assert T % n == 0, f"--seq-len must divide over {n} shards"

    cfg = GPT2Config(vocab_size=512, max_seq_len=T, num_layers=2,
                     num_heads=4, d_model=128, dtype=jnp.float32,
                     use_ring_attention=True, ring_layout="striped",
                     attention="flash" if args.flash else "dense")
    model = GPT2(cfg)

    rng = np.random.default_rng(0)
    tokens_global = jnp.asarray(
        rng.integers(0, cfg.vocab_size, (args.batch, T)), jnp.int32)
    # Striped layout: shard r must hold positions r, r+n, r+2n, ... — lay
    # the sequence out stride-major so shard_map's contiguous split does it.
    striped = tokens_global.reshape(args.batch, T // n, n) \
        .swapaxes(1, 2).reshape(args.batch, T)

    # Param init traces no ring ops: use the plain config on a short stub.
    params = GPT2(GPT2Config(
        vocab_size=cfg.vocab_size, max_seq_len=T, num_layers=2,
        num_heads=4, d_model=128, dtype=jnp.float32)).init(
            jax.random.PRNGKey(0), tokens_global[:, :8])

    opt = hvd.DistributedOptimizer(optax.adamw(args.lr))
    opt_state = opt.init(params["params"])

    def step(params, opt_state, toks):
        def loss_fn(p):
            logits = model.apply({"params": p}, toks)
            return striped_lm_loss(logits, toks)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state2 = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state2, loss

    spmd_step = hvd.spmd(step,
                         in_specs=(P(), P(), P(None, "sp")),
                         out_specs=(P(), P(), P()))

    losses = []
    p = params["params"]
    for i in range(args.steps):
        p, opt_state, loss = spmd_step(p, opt_state, striped)
        losses.append(float(loss))
        print(f"step {i}: loss {losses[-1]:.4f} "
              f"(T={T} over {n} sp shards, {T // n}/shard)")
    assert losses[-1] < losses[0], losses
    print("OK")


if __name__ == "__main__":
    main()
