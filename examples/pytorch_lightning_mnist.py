"""Upstream-shaped Lightning training script (mirrors
``examples/pytorch/pytorch_lightning_mnist.py`` in the reference): the
LightningModule is standard; distribution comes from
``horovod_tpu.lightning.HorovodStrategy`` (with pytorch-lightning
installed, pass the strategy to ``pl.Trainer``; the bundled ``Trainer``
drives the same protocol without the dependency). Synthetic MNIST-shaped
data.

Run:  python examples/pytorch_lightning_mnist.py --epochs 4
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.02)
    args = ap.parse_args()

    import torch
    import torch.nn.functional as F

    from horovod_tpu.data import DistributedSampler
    from horovod_tpu.lightning import HorovodStrategy, Trainer

    # --- a standard LightningModule-shaped model ---------------------------
    class LitMnist(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.conv = torch.nn.Conv2d(1, 10, kernel_size=5)
            self.fc1 = torch.nn.Linear(10 * 12 * 12, 50)
            self.fc2 = torch.nn.Linear(50, 10)

        def forward(self, x):
            x = F.relu(F.max_pool2d(self.conv(x), 2))
            x = F.relu(self.fc1(x.flatten(1)))
            return F.log_softmax(self.fc2(x), dim=1)

        def training_step(self, batch, batch_idx):
            data, target = batch
            return F.nll_loss(self(data), target)

        def configure_optimizers(self):
            return torch.optim.SGD(self.parameters(), lr=args.lr,
                                   momentum=0.5)

    torch.manual_seed(42)
    model = LitMnist()

    rng = np.random.default_rng(0)
    n = args.batch * 4
    images = torch.from_numpy(
        rng.standard_normal((n, 1, 28, 28)).astype(np.float32))
    labels = torch.from_numpy(rng.integers(0, 10, (n,)).astype(np.int64))

    strategy = HorovodStrategy()
    sampler = DistributedSampler(n, rank=strategy.global_rank,
                                 size=strategy.world_size)
    idx = torch.as_tensor(np.asarray(list(iter(sampler))))
    loader = [(images[i], labels[i])
              for i in torch.split(idx, args.batch)]

    trainer = Trainer(max_epochs=args.epochs, strategy=strategy)
    trainer.fit(model, loader)

    first, last = trainer.history[0], trainer.history[-1]
    if strategy.is_global_zero:
        print(f"loss {first:.4f} -> {last:.4f}")
    assert last < first, "training did not reduce the loss"
    print("OK")


if __name__ == "__main__":
    main()
