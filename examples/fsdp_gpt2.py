"""FSDP / ZeRO-3 GPT-2 training (the DeepSpeed-ZeRO-3-on-hvd role,
TPU-native): transformer blocks stored as 1/n flat shards per device,
gathered just in time inside the layer scan, gradients leaving each block
as one fused psum_scatter, and a shard-domain AdamW that never
all-gathers updates — peak parameter memory is |params|/n + one block.

Run:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/fsdp_gpt2.py --steps 5
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--layers", type=int, default=4)
    args = ap.parse_args()

    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.models.gpt2 import GPT2, Block, GPT2Config, loss_fn
    from horovod_tpu.optimizer_sharded import ShardedAdamWState
    from horovod_tpu.parallel.fsdp import (flat_size, fsdp_adamw,
                                           fsdp_scan_blocks,
                                           stack_layer_shards)

    hvd.init()
    n = hvd.size()
    cfg = GPT2Config(vocab_size=256, max_seq_len=64,
                     num_layers=args.layers, num_heads=4, d_model=64,
                     dtype=jnp.float32)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (n, 4, 32)),
                         jnp.int32)

    params = GPT2(cfg).init(jax.random.PRNGKey(0),
                            tokens.reshape(-1, 32))["params"]
    layer_keys = sorted((k for k in params if k.startswith("h")),
                        key=lambda k: int(k[1:]))
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *[params[k] for k in layer_keys])
    rest = {k: v for k, v in params.items() if not k.startswith("h")}
    rows = stack_layer_shards(stacked)
    template = params[layer_keys[0]]
    total = flat_size(stacked)
    print(f"{total:,} block params stored as {rows.shape} "
          f"({rows.size // n:,} per device — 1/{n})")

    block = Block(cfg)
    ln_f = nn.LayerNorm(dtype=jnp.float32)
    opt = fsdp_adamw(1e-3)
    state = opt.init(rows.reshape(-1))

    def step(rows, mu, nu, stepc, rest, toks):
        def loss(rows):
            T = toks.shape[-1]
            h = (rest["wte"][toks[0]].astype(cfg.dtype)
                 + rest["wpe"][jnp.arange(T)].astype(cfg.dtype))
            h = fsdp_scan_blocks(
                lambda p, hh: block.apply({"params": p}, hh),
                template, rows, h)
            h = ln_f.apply({"params": rest["ln_f"]}, h)
            logits = jnp.einsum("btd,vd->btv", h.astype(jnp.float32),
                                rest["wte"])
            return loss_fn(logits, toks[0])

        l, g_rows = jax.value_and_grad(loss)(rows)
        L = g_rows.shape[0]
        upd, st2 = opt.update(g_rows.reshape(-1),
                              ShardedAdamWState(stepc, mu, nu),
                              rows.reshape(-1))
        return (rows + upd.reshape(L, -1), st2.mu, st2.nu, st2.step,
                jax.lax.pmean(l, "hvd"))

    fn = hvd.spmd(step,
                  in_specs=(P(None, "hvd"), P("hvd"), P("hvd"),
                            P("hvd"), P(), P("hvd")),
                  out_specs=(P(None, "hvd"), P("hvd"), P("hvd"),
                             P("hvd"), P()))

    mu, nu, stepc = state.mu, state.nu, state.step
    losses = []
    for i in range(args.steps):
        rows, mu, nu, stepc, l = fn(rows, mu, nu, stepc, rest, tokens)
        losses.append(float(l))
        print(f"step {i}: loss {losses[-1]:.4f}")
    assert losses[-1] < losses[0], losses
    print("FSDP OK: loss decreased with 1/n-sharded parameters")


if __name__ == "__main__":
    main()
