"""Elastic FSDP/ZeRO-3 training: a flat-shard state surviving a re-mesh
(upstream analogue: ``horovod/common/elastic.py`` state semantics over
DeepSpeed-ZeRO-on-hvd).

The ZeRO-3 state is world-size-DEPENDENT — each device owns a ``(c,)``
chunk of the padded flat parameter/optimizer vectors with
``c = ceil(len/n)`` — so an elastic resume cannot replay raw snapshots
the way ``JaxState`` does. :class:`~horovod_tpu.elastic.FsdpState`
commits a canonical (padding-stripped) form and re-pads for whatever
communicator exists after recovery; the flat AdamW math is elementwise,
so training continues numerically as if the mesh never changed.

Preemption is simulated on the virtual mesh (half the devices drop after
a few steps) so the recovery path actually executes:

  JAX_PLATFORMS=cpu python examples/fsdp_elastic.py
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # Force the platform via config: env-var-only selection can still try to
    # initialize an accelerator plugin registered at interpreter startup.
    import jax
    jax.config.update("jax_platforms", "cpu")

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.elastic import FsdpState, HostsUpdatedInterrupt, run
from horovod_tpu.elastic.discovery import DeviceDiscovery
from horovod_tpu.parallel.fsdp import (fsdp_adamw, fsdp_apply,
                                       fsdp_shard_params)

TOTAL_STEPS = 10
PREEMPT_AT = 5
D = 16


def _mlp_template():
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    return {
        "w1": jax.random.normal(k1, (D, 2 * D), jnp.float32) * 0.3,
        "b1": jnp.zeros((2 * D,), jnp.float32),
        "w2": jax.random.normal(k2, (2 * D, D), jnp.float32) * 0.3,
        "b2": jnp.zeros((D,), jnp.float32),
    }


def _block(p, x):
    h = jax.nn.relu(x @ p["w1"] + p["b1"])
    return x + h @ p["w2"] + p["b2"]


def main():
    hvd.init()
    all_devs = jax.devices()
    current = {"devs": all_devs}
    disco = DeviceDiscovery(probe=lambda: current["devs"])

    template = _mlp_template()
    tx = fsdp_adamw(0.05)
    shard = fsdp_shard_params(template)
    state = FsdpState(template, shard=shard, opt_state=tx.init(shard),
                      step=0)
    rng = np.random.default_rng(0)

    def make_step():
        def body(shard, opt_state, xs):
            def loss(s):
                return jnp.mean(fsdp_apply(_block, template, s, xs) ** 2)

            l, g = jax.value_and_grad(loss)(shard)
            upd, opt_state = tx.update(g, opt_state, shard)
            # The gradient is already the dp mean (fsdp's psum_scatter);
            # the reported loss needs its own pmean to be the GLOBAL
            # batch mean rather than one device's slice.
            return (optax.apply_updates(shard, upd), opt_state,
                    jax.lax.pmean(l, "hvd"))

        return hvd.spmd(body, in_specs=(P("hvd"), P("hvd"), P("hvd")),
                        out_specs=(P("hvd"), P("hvd"), P()))

    @run
    def train(state):
        step_fn = make_step()        # retraces against the current mesh
        n = hvd.size()
        c = state.shard.shape[0] // n
        print(f"[world {n}: {c} params/device of "
              f"{state.shard.shape[0]} padded]")
        while state.step < TOTAL_STEPS:
            if (state.step == PREEMPT_AT
                    and len(current["devs"]) == len(all_devs)
                    and len(all_devs) > 1):
                current["devs"] = all_devs[:max(1, len(all_devs) // 2)]
                print(f"[simulated preemption at step {state.step}]")
                raise HostsUpdatedInterrupt("preempted")
            # Fixed global batch regardless of world size: per-device
            # means over equal slices combine to the same global mean.
            X = jnp.asarray(rng.standard_normal((8, D)), jnp.float32)
            state.shard, state.opt_state, loss = step_fn(
                state.shard, state.opt_state, X)
            state.step += 1
            state.commit()
            print(f"step {state.step} on {n} devices: "
                  f"loss={float(loss):.5f}")

    train(state, discovery=disco)
    print(f"done: {state.step} steps, final communicator size "
          f"{hvd.size()}, shard re-padded to {state.shard.shape[0]}")
    assert state.step == TOTAL_STEPS


if __name__ == "__main__":
    main()
