"""Llama-family training (RoPE + RMSNorm + SwiGLU + GQA) with dp x tp
sharding — the modern-LLM analogue of the reference's framework-native
example scripts (upstream horovod/examples): Megatron partition rules +
GSPMD insert the collectives, GQA keeps the kv parameter/optimizer
footprint at num_kv_heads/num_heads of MHA.
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # Force the platform via config: env-var-only selection can still try to
    # initialize an accelerator plugin registered at interpreter startup.
    import jax
    jax.config.update("jax_platforms", "cpu")


import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models.llama import (
    Llama, LlamaConfig, loss_fn, partition_rules,
)
from horovod_tpu.parallel import make_mesh, shard_pytree


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--d-model", type=int, default=64)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--kv-heads", type=int, default=2)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--steps", type=int, default=5)
    args = ap.parse_args()

    hvd.init()
    n = hvd.size()
    if n % args.tp:
        raise SystemExit(f"--tp {args.tp} must divide world size {n}")
    dp = n // args.tp
    mesh = make_mesh({"dp": dp, "tp": args.tp})

    cfg = LlamaConfig(vocab_size=256, max_seq_len=args.seq,
                      num_layers=args.layers, num_heads=args.heads,
                      num_kv_heads=args.kv_heads, d_model=args.d_model,
                      d_ff=2 * args.d_model)
    model = Llama(cfg)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(
        rng.integers(0, cfg.vocab_size, (args.batch * dp, args.seq)),
        jnp.int32)

    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    params = shard_pytree(params, mesh, partition_rules())
    tokens = jax.device_put(tokens, NamedSharding(mesh, P("dp")))

    opt = hvd.DistributedOptimizer(optax.adamw(3e-3))
    opt_state = opt.init(params)

    def train_step(params, opt_state, tokens):
        l, grads = jax.value_and_grad(
            lambda p: loss_fn(model.apply({"params": p}, tokens),
                              tokens))(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, l

    with jax.set_mesh(mesh) if hasattr(jax, "set_mesh") else mesh:
        step = jax.jit(train_step, donate_argnums=(0, 1))
        first = l = None
        for i in range(args.steps):
            params, opt_state, l = step(params, opt_state, tokens)
            l = float(l)
            first = first if first is not None else l
            print(f"step {i}: loss {l:.4f}", flush=True)
    if hvd.rank() == 0 and l is not None:
        kv_frac = cfg.num_kv_heads / cfg.num_heads
        print(f"final loss {l:.4f} (first {first:.4f}); "
              f"GQA kv heads at {kv_frac:.0%} of MHA")
        if args.steps > 1:
            assert l < first, "loss did not decrease"


if __name__ == "__main__":
    main()
