"""Durable-store estimator flow (upstream ``horovod.spark`` + its
``common/store.py`` / petastorm data path): materialise a dataset into a
Store once, train with workers streaming ONLY their shard partition, and
reload the trained weights from the store's checkpoint directory — no
DataFrame or driver arrays anywhere near the workers after staging.

Run:
    python examples/estimator_store.py --workers 2 [--store /tmp/hvd_store]
"""

import os
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # Force the platform via config: env-var-only selection can still try to
    # initialize an accelerator plugin registered at interpreter startup.
    import jax
    jax.config.update("jax_platforms", "cpu")

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--epochs", type=int, default=12)
    ap.add_argument("--store", default=None,
                    help="store path or fsspec URL (default: a temp dir)")
    args = ap.parse_args()

    import contextlib

    from horovod_tpu.data.store import Store

    # ExitStack: the temp store is removed even when training or an
    # assertion below fails.
    with contextlib.ExitStack() as stack:
        if args.store is None:
            args.store = stack.enter_context(
                tempfile.TemporaryDirectory(prefix="hvd_store_"))
        _run_demo(args, Store.create(args.store))


def _run_demo(args, store):
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np

    from horovod_tpu.cluster import LocalProcessBackend
    from horovod_tpu.data.store import read_meta
    from horovod_tpu.spark import JaxEstimator, load_checkpoint

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            h = nn.relu(nn.Dense(16)(x))
            return nn.Dense(1)(h)[..., 0]

    def mse(pred, label):
        return jnp.mean((pred - label) ** 2)

    rng = np.random.default_rng(0)
    X = rng.standard_normal((256, 4)).astype(np.float32)
    y = (X @ np.array([1.0, -2.0, 0.5, 0.8], np.float32)).astype(np.float32)

    est = JaxEstimator(
        MLP(), mse, lr=0.05, epochs=args.epochs, batch_size=16,
        store=store, run_id="demo", num_shards=2 * args.workers,
        validation=0.125,           # held out, materialised separately
        backend=LocalProcessBackend(args.workers, coordinator_port=29820))

    model = est.fit({"features": X, "label": y})

    meta = read_meta(store, store.train_data_path("demo"))
    print(f"staged {meta['total_rows']} rows as {len(meta['shards'])} "
          f"{meta['format']} shards under {store.prefix} "
          f"(+ {read_meta(store, store.val_data_path('demo'))['total_rows']}"
          f" val rows)")
    for r in est.last_fit_results:
        print(f"  rank {r['rank']}: read only {r['files_read']}, "
              f"loss {r['history'][0]:.3f} -> {r['history'][-1]:.3f}")
    hist = model.get_history()
    print(f"val loss per epoch: "
          f"{[round(v, 3) for v in hist['val_loss']]}")
    assert hist["val_loss"][-1] < hist["val_loss"][0]

    # The same composed pipeline the workers trained through, user-side:
    # background shard reads + in-flight device_puts (data/prefetch.py).
    from horovod_tpu.data.store import ShardedDatasetReader
    reader = ShardedDatasetReader(store, store.train_data_path("demo"))
    with reader.prefetched_batches(16, shuffle=False) as batches:
        dev_losses = [float(mse(model.predict(b["features"]), b["label"]))
                      for b in batches]
    print(f"store-side eval over {len(dev_losses)} prefetched "
          f"device batches: {np.mean(dev_losses):.4f}")
    reads = [set(r["files_read"]) for r in est.last_fit_results]
    assert set.union(*reads) == {s["file"] for s in meta["shards"]}
    assert not set.intersection(*reads), "partitions must be disjoint"

    # The trained weights are durable too: reload them store-side.
    ckpt = load_checkpoint(store, "demo")
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        ckpt["params"], model.params)
    pred = model.predict(X[:4])
    print(f"reloaded checkpoint matches; predictions {np.round(pred, 2)}")


if __name__ == "__main__":
    main()
