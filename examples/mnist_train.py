"""MNIST CNN training — the framework's hello-world, mirroring the reference
example ``examples/tensorflow2/tensorflow2_keras_mnist.py`` on the JAX
frontend (synthetic data: no datasets ship in the image).

Run single-host:      python examples/mnist_train.py
Virtual 8-dev CPU:    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
                      JAX_PLATFORMS=cpu python examples/mnist_train.py
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # Force the platform via config: env-var-only selection can still try to
    # initialize an accelerator plugin registered at interpreter startup.
    import jax
    jax.config.update("jax_platforms", "cpu")


import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.callbacks import MetricAverageCallback, warmup_schedule
from horovod_tpu.models import MnistCNN


def main(epochs: int = 2, steps_per_epoch: int = 10, batch: int = 32):
    hvd.init()
    print(f"communicator: size={hvd.size()} backend={jax.default_backend()}")

    model = MnistCNN()
    rng = np.random.default_rng(42)
    x0 = jnp.zeros((batch, 28, 28, 1), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x0, train=False)
    params = variables["params"]

    # Horovod recipe: scale LR by size with warmup, then wrap the optimizer.
    sched = warmup_schedule(1e-3, warmup_epochs=1,
                            steps_per_epoch=steps_per_epoch)
    opt = hvd.DistributedOptimizer(optax.adam(sched),
                                   compression=hvd.Compression.bf16)
    opt_state = opt.init(params)

    def train_step(params, opt_state, images, labels):
        params = hvd.broadcast_parameters(params, root_rank=0)

        def loss_fn(p):
            logits = model.apply({"params": p}, images, train=False)
            return -jnp.mean(jnp.take_along_axis(
                jax.nn.log_softmax(logits), labels[:, None], 1))

        loss, grads = hvd.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    step = hvd.spmd(train_step,
                    in_specs=(P(), P(), P("hvd"), P("hvd")),
                    out_specs=(P(), P(), P()))

    metric_cb = MetricAverageCallback()
    n = hvd.size()
    for epoch in range(epochs):
        losses = []
        for _ in range(steps_per_epoch):
            images = jnp.asarray(
                rng.standard_normal((batch * n, 28, 28, 1)), jnp.float32)
            labels = jnp.asarray(rng.integers(0, 10, (batch * n,)), jnp.int32)
            params, opt_state, loss = step(params, opt_state, images, labels)
            losses.append(float(loss))
        avg = metric_cb.on_epoch_end({"loss": float(np.mean(losses))})
        print(f"epoch {epoch}: loss={float(avg['loss']):.4f}")


if __name__ == "__main__":
    main()
