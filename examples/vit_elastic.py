"""ViT elastic training (reference config "ViT-B/16 elastic training,
preemptible v5e"): JaxState commit/restore + hvd.elastic.run around the
train loop. Preemption is simulated on the virtual mesh (drop half the
devices after a few steps) so the recovery path actually executes.
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # Force the platform via config: env-var-only selection can still try to
    # initialize an accelerator plugin registered at interpreter startup.
    import jax
    jax.config.update("jax_platforms", "cpu")


import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.elastic import JaxState, run, HostsUpdatedInterrupt
from horovod_tpu.elastic.discovery import DeviceDiscovery
from horovod_tpu.models.vit import ViT, ViTConfig

TOTAL_STEPS = 10
PREEMPT_AT = 5


def main():
    hvd.init()
    all_devs = jax.devices()
    current = {"devs": all_devs}
    disco = DeviceDiscovery(probe=lambda: current["devs"])

    cfg = ViTConfig.tiny()
    model = ViT(cfg)
    rng = np.random.default_rng(0)
    x0 = jnp.zeros((2, cfg.image_size, cfg.image_size, 3))
    params = model.init(jax.random.PRNGKey(0), x0)["params"]
    opt = optax.adam(1e-3)
    state = JaxState(params=params, opt_state=opt.init(params), step=0)

    def make_step():
        def train_step(params, opt_state, images, labels):
            def loss_fn(p):
                logits = model.apply({"params": p}, images)
                return -jnp.mean(jnp.take_along_axis(
                    jax.nn.log_softmax(logits), labels[:, None], 1))

            loss, grads = hvd.value_and_grad(loss_fn)(params)
            updates, opt_state2 = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state2, loss

        return hvd.spmd(train_step,
                        in_specs=(P(), P(), P("hvd"), P("hvd")),
                        out_specs=(P(), P(), P()))

    @run
    def train(state):
        step_fn = make_step()  # retraces against the current mesh
        n = hvd.size()
        while state.step < TOTAL_STEPS:
            if state.step == PREEMPT_AT and len(current["devs"]) == len(all_devs) \
                    and len(all_devs) > 1:
                current["devs"] = all_devs[:max(1, len(all_devs) // 2)]
                print(f"[simulated preemption at step {state.step}]")
                raise HostsUpdatedInterrupt("preempted")
            images = jnp.asarray(rng.standard_normal(
                (2 * n, cfg.image_size, cfg.image_size, 3)), jnp.float32)
            labels = jnp.asarray(rng.integers(0, cfg.num_classes, (2 * n,)),
                                 jnp.int32)
            state.params, state.opt_state, loss = step_fn(
                state.params, state.opt_state, images, labels)
            state.step += 1
            state.commit()
            print(f"step {state.step} on {n} devices: loss={float(loss):.4f}")

    train(state, discovery=disco)
    print(f"done: {state.step} steps, final communicator size {hvd.size()}")


if __name__ == "__main__":
    main()
