"""Spark/Ray-style orchestration: train a model through the estimator
fit/transform state machine and run functions on a worker fleet via the
RayExecutor — both against the injected cluster backend (local processes
here; a ray/Spark cluster binds the same contract when those packages
exist).

Run:
    python examples/estimator_cluster.py --workers 2
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # Force the platform via config: env-var-only selection can still try to
    # initialize an accelerator plugin registered at interpreter startup.
    import jax
    jax.config.update("jax_platforms", "cpu")

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--epochs", type=int, default=10)
    args = ap.parse_args()

    import flax.linen as nn
    import jax.numpy as jnp

    from horovod_tpu.cluster import LocalProcessBackend
    from horovod_tpu.ray import RayExecutor
    from horovod_tpu.spark import JaxEstimator

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            h = nn.relu(nn.Dense(16)(x))
            return nn.Dense(1)(h)[..., 0]

    def mse(pred, label):
        return jnp.mean((pred - label) ** 2)

    rng = np.random.default_rng(0)
    X = rng.standard_normal((256, 4)).astype(np.float32)
    y = (np.sin(X[:, 0]) + 0.5 * X[:, 1]).astype(np.float32)

    # --- Estimator: fit on partitioned data, transform on the driver ------
    est = JaxEstimator(MLP(), mse, lr=5e-3, epochs=args.epochs,
                       batch_size=32,
                       backend=LocalProcessBackend(args.workers))
    model = est.fit({"features": X, "label": y})
    hist = est.last_fit_results[0]["history"]
    print(f"estimator: {args.workers} workers, loss {hist[0]:.4f} -> "
          f"{hist[-1]:.4f}")
    out = model.transform({"features": X, "label": y})
    print("transform residual:",
          float(np.abs(out["prediction"] - y).mean()))

    # --- RayExecutor: run a function on every rendezvoused worker ---------
    ex = RayExecutor(backend=LocalProcessBackend(args.workers,
                                                 coordinator_port=29960))
    ex.start()

    def report():
        import jax

        import horovod_tpu as hvd
        return {"rank": jax.process_index(), "world": jax.process_count(),
                "backend": jax.default_backend(),
                "build": hvd.build_info()["backend"]}

    for r in ex.run(report):
        print("worker:", r)
    ex.shutdown()


if __name__ == "__main__":
    main()
