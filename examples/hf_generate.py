"""Load a HuggingFace checkpoint, fine-tune a step, generate — the
migration loop end-to-end (convert -> train -> decode).

Uses a random-init HF model (this image has no network for pretrained
downloads); with connectivity, `GPT2LMHeadModel.from_pretrained("gpt2")`
drops in unchanged. The demo proves the loop the way the test suite
does: our greedy decode matches HF `generate()` token-for-token on the
same weights, then one fine-tune step shifts the continuation.

Run:
  JAX_PLATFORMS=cpu python examples/hf_generate.py
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # Force the platform via config: env-var-only selection can still try to
    # initialize an accelerator plugin registered at interpreter startup.
    import jax
    jax.config.update("jax_platforms", "cpu")

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu.models.convert import gpt2_from_hf
from horovod_tpu.models.generate import generate
from horovod_tpu.models.gpt2 import loss_fn


def main():
    import torch
    import transformers

    hvd.init()
    torch.manual_seed(0)
    hf = transformers.GPT2LMHeadModel(transformers.GPT2Config(
        vocab_size=256, n_positions=128, n_embd=64, n_layer=2,
        n_head=4)).eval()
    model, params = gpt2_from_hf(hf)

    rng = np.random.default_rng(0)
    prompt = rng.integers(1, 256, (2, 8))

    # 1. Parity: same weights, same greedy continuation as HF.
    with torch.no_grad():
        theirs = hf.generate(torch.from_numpy(prompt), max_new_tokens=12,
                             do_sample=False, pad_token_id=0).numpy()
    ours = np.asarray(generate(model, params,
                               jnp.asarray(prompt, jnp.int32), 12))
    assert (ours == theirs).all(), "greedy decode diverged from HF"
    print(f"greedy decode == hf.generate over {ours.shape[1]} tokens")

    # 2. Fine-tune one step on a synthetic batch...
    toks = jnp.asarray(rng.integers(1, 256, (4, 32)), jnp.int32)
    opt = hvd.DistributedOptimizer(optax.adamw(1e-2))
    ost = opt.init(params)

    @jax.jit
    def step(p, ost):
        l, g = jax.value_and_grad(
            lambda p: loss_fn(model.apply({"params": p}, toks), toks))(p)
        u, ost = opt.update(g, ost, p)
        return optax.apply_updates(p, u), ost, l

    params2, ost, l = step(jax.tree_util.tree_map(jnp.asarray, params),
                           ost)
    print(f"fine-tune step: loss {float(l):.4f}")

    # 3. ...and sample from the updated weights.
    sampled = generate(model, params2, jnp.asarray(prompt, jnp.int32), 12,
                       temperature=0.8, top_k=40,
                       rng=jax.random.PRNGKey(0))
    print(f"sampled continuation (post-finetune): "
          f"{np.asarray(sampled)[0, 8:].tolist()}")


if __name__ == "__main__":
    main()
