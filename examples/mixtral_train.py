"""Mixtral-style MoE training: Llama blocks with top-2-routed SwiGLU
experts sharded over the ep mesh axis (upstream's role here is its
framework-native example scripts, ``horovod/examples``; experts-on-hvd
is the DeepSpeed-MoE layering the reference ecosystem uses).

dp x ep x tp: the router's dispatch/combine einsums contract a
token-sharded axis against expert-sharded weights, which is exactly
where GSPMD inserts the expert all-to-alls — no hand-written
communication. The aux load-balance loss comes back through the sown
"losses" collection (``loss_fn_moe``).

Run (single device or the virtual CPU mesh):
  JAX_PLATFORMS=cpu python examples/mixtral_train.py --steps 3
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # Force the platform via config: env-var-only selection can still try to
    # initialize an accelerator plugin registered at interpreter startup.
    import jax
    jax.config.update("jax_platforms", "cpu")

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models.llama import (
    Llama, LlamaConfig, loss_fn_moe, partition_rules,
)
from horovod_tpu.parallel import make_mesh, shard_pytree


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--experts", type=int, default=4)
    ap.add_argument("--ep", type=int, default=None,
                    help="expert-parallel size (default: 2 if it divides "
                         "the world, else 1)")
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--steps", type=int, default=5)
    args = ap.parse_args()

    hvd.init()
    n = hvd.size()
    ep = args.ep if args.ep is not None else (2 if n % 2 == 0 else 1)
    if n % (ep * args.tp):
        raise SystemExit(f"ep*tp {ep * args.tp} must divide world {n}")
    dp = n // (ep * args.tp)
    mesh = make_mesh({"dp": dp, "ep": ep, "tp": args.tp})

    cfg = LlamaConfig.tiny(num_experts=args.experts,
                           max_seq_len=args.seq)
    model = Llama(cfg)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(
        rng.integers(0, cfg.vocab_size, (args.batch * dp, args.seq)),
        jnp.int32)

    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    params = shard_pytree(params, mesh, partition_rules())
    tokens = jax.device_put(tokens, NamedSharding(mesh, P("dp")))

    opt = hvd.DistributedOptimizer(optax.adamw(3e-3))
    opt_state = opt.init(params)

    def train_step(params, opt_state, tokens):
        l, grads = jax.value_and_grad(
            lambda p: loss_fn_moe(model, p, tokens))(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, l

    with jax.set_mesh(mesh) if hasattr(jax, "set_mesh") else mesh:
        step = jax.jit(train_step, donate_argnums=(0, 1))
        first = l = None
        for i in range(args.steps):
            params, opt_state, l = step(params, opt_state, tokens)
            l = float(l)
            first = first if first is not None else l
            print(f"step {i}: loss {l:.4f}", flush=True)
    if hvd.rank() == 0 and l is not None:
        n_expert_params = sum(
            int(np.prod(v.shape))
            for path, v in jax.tree_util.tree_leaves_with_path(params)
            if "/".join(str(k.key) for k in path).endswith(
                ("w_gate", "w_in", "w_out")))
        print(f"final loss {l:.4f} (first {first:.4f}); "
              f"{args.experts} SwiGLU experts, top-2 routed, "
              f"{n_expert_params:,} expert params over ep={ep}")
        if args.steps > 1:
            assert l < first, "loss did not decrease"


if __name__ == "__main__":
    main()
