"""Upstream-shaped tf.keras training script (mirrors
``examples/tensorflow2/tensorflow2_keras_mnist.py`` in the reference): the
intended diff for a migrating user is the import — ``import
horovod.tensorflow.keras as hvd`` becomes ``import
horovod_tpu.tensorflow.keras as hvd``. Synthetic MNIST-shaped data.

Run:  python examples/tensorflow2_keras_mnist.py --epochs 3
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.001)
    args = ap.parse_args()

    import tensorflow as tf

    import horovod_tpu.tensorflow.keras as hvd

    # --- the upstream script body, unchanged in structure ------------------
    hvd.init()
    tf.keras.utils.set_random_seed(42)   # deterministic weight init

    rng = np.random.default_rng(0)
    n = args.batch * 4 * hvd.size()      # 4 steps/epoch per worker
    images = rng.standard_normal((n, 28, 28, 1)).astype(np.float32)
    labels = rng.integers(0, 10, (n,)).astype(np.int64)
    # Upstream shards with dataset.shard(hvd.size(), hvd.rank()).
    dataset = (tf.data.Dataset.from_tensor_slices((images, labels))
               .shard(hvd.size(), hvd.rank())
               .shuffle(1024, seed=42).batch(args.batch).repeat())

    model = tf.keras.Sequential([
        tf.keras.layers.Input((28, 28, 1)),
        tf.keras.layers.Conv2D(16, 3, activation="relu"),
        tf.keras.layers.MaxPooling2D(),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(64, activation="relu"),
        tf.keras.layers.Dense(10, activation="softmax"),
    ])

    # Upstream scales the LR by the number of workers and wraps the
    # optimizer; callbacks sync initial state and average metrics.
    opt = hvd.DistributedOptimizer(
        tf.keras.optimizers.Adam(args.lr * hvd.size()))
    model.compile(optimizer=opt,
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])

    callbacks = [
        hvd.BroadcastGlobalVariablesCallback(root_rank=0),
        hvd.MetricAverageCallback(),
        hvd.LearningRateWarmupCallback(initial_lr=args.lr * hvd.size(),
                                       warmup_epochs=1, verbose=0),
    ]

    steps_per_epoch = max(1, n // hvd.size() // args.batch)
    hist = model.fit(dataset, steps_per_epoch=steps_per_epoch,
                     epochs=args.epochs, callbacks=callbacks,
                     verbose=1 if hvd.rank() == 0 else 0)

    first, last = hist.history["loss"][0], hist.history["loss"][-1]
    print(f"loss {first:.4f} -> {last:.4f}")
    assert last < first, "training did not reduce the loss"
    print("OK")


if __name__ == "__main__":
    main()
