"""T5 encoder-decoder seq2seq training with dp x tp sharding (upstream's
role here is its framework-native example scripts, ``horovod/examples``;
this completes the zoo's architecture classes next to the decoder-only
and encoder-only examples).

The synthetic task is learnable: the target is the source reversed, so
cross-attention has real structure to find. Padding exercises both mask
paths (encoder self-attn + cross-attn ignore source pads; pad labels
carry no loss).

Run (single device or the virtual CPU mesh):
  JAX_PLATFORMS=cpu python examples/t5_train.py --steps 5
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # Force the platform via config: env-var-only selection can still try to
    # initialize an accelerator plugin registered at interpreter startup.
    import jax
    jax.config.update("jax_platforms", "cpu")

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models.t5 import (T5, T5Config, partition_rules,
                                   seq2seq_loss)
from horovod_tpu.parallel import make_mesh, shard_pytree


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tp", type=int, default=None,
                    help="tensor-parallel size (default: 2 if it divides "
                         "the world, else 1)")
    ap.add_argument("--seq", type=int, default=24)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--steps", type=int, default=8)
    args = ap.parse_args()

    hvd.init()
    n = hvd.size()
    tp = args.tp if args.tp is not None else (2 if n % 2 == 0 else 1)
    if n % tp:
        raise SystemExit(f"--tp {tp} must divide world size {n}")
    dp = n // tp
    mesh = make_mesh({"dp": dp, "tp": tp})

    cfg = T5Config.tiny()
    model = T5(cfg)
    rng = np.random.default_rng(0)
    # Reversal task with ragged source lengths -> real padding.
    B = args.batch * dp
    src = np.full((B, args.seq), cfg.pad_id, np.int64)
    tgt = np.full((B, args.seq), cfg.pad_id, np.int64)
    for b in range(B):
        ln = rng.integers(args.seq // 2, args.seq + 1)
        row = rng.integers(1, cfg.vocab_size, ln)
        src[b, :ln] = row
        tgt[b, :ln] = row[::-1]
    src, tgt = jnp.asarray(src, jnp.int32), jnp.asarray(tgt, jnp.int32)

    from horovod_tpu.models.t5 import shift_right
    params = model.init(jax.random.PRNGKey(0), src,
                        shift_right(tgt, cfg.pad_id))["params"]
    params = shard_pytree(params, mesh, partition_rules())
    src = jax.device_put(src, NamedSharding(mesh, P("dp")))
    tgt = jax.device_put(tgt, NamedSharding(mesh, P("dp")))

    opt = hvd.DistributedOptimizer(optax.adamw(3e-3))
    opt_state = opt.init(params)

    def train_step(params, opt_state, src, tgt):
        l, grads = jax.value_and_grad(
            lambda p: seq2seq_loss(model, p, src, tgt))(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, l

    with jax.set_mesh(mesh) if hasattr(jax, "set_mesh") else mesh:
        step = jax.jit(train_step, donate_argnums=(0, 1))
        first = l = None
        for i in range(args.steps):
            params, opt_state, l = step(params, opt_state, src, tgt)
            l = float(l)
            first = first if first is not None else l
            print(f"step {i}: loss {l:.4f}", flush=True)
    if hvd.rank() == 0 and l is not None:
        print(f"final seq2seq loss {l:.4f} (first {first:.4f}) over "
              f"dp={dp} tp={tp}")
        if args.steps > 1:
            assert l < first, "loss did not decrease"


if __name__ == "__main__":
    main()
