# Developer entry points. The native library has its own Makefile (cpp/).

PY ?= python

.PHONY: trace-smoke overlap-smoke serve-smoke doctor-smoke quant-smoke \
	preempt-smoke topo-smoke net-smoke fleet-smoke prefix-smoke \
	mp-smoke reqtrace-smoke config-smoke fleet-top postmortem \
	bench-sentinel test native

# Cross-rank tracing smoke: 2 CPU processes with HOROVOD_TIMELINE shards,
# merged via hvd.merge_timelines; exits nonzero if the merged trace is
# invalid JSON, the straggler report is empty, or the NEGOTIATE/QUEUE/EXEC
# phases of a collective don't share one op-id across ranks. Also runs in
# tier-1 as tests/test_trace_merge.py::TestTwoProcessSmoke.
trace-smoke:
	$(PY) tools/trace_smoke.py

# Overlapped gradient-sync smoke: 2 CPU processes run the same tiny train
# loop with the monolithic psum and the chunked RS+AG pipeline and must
# land on identical parameters on every rank. Also runs in tier-1 as
# tests/test_overlap.py::TestTwoProcessSmoke.
overlap-smoke:
	$(PY) tools/overlap_smoke.py

# Multi-replica serving smoke: 2 CPU replica processes share a request
# spool, overlapping streaming requests land on both, one replica is
# SIGKILLed mid-stream, and the survivor must reclaim its orphaned
# claims (stale heartbeat) and drain the whole queue. Also runs in
# tier-1 as tests/test_serving.py::TestTwoProcessSmoke.
serve-smoke:
	$(PY) tools/serve_smoke.py

# Doctor smoke: 2 CPU processes with a manufactured 250ms straggler and a
# forced recompile (static arg change); hvd.doctor() over the merged trace
# + fused metrics snapshots must rank both — the straggler naming rank 1,
# the recompile naming the blamed argument. Also runs in tier-1 as
# tests/test_doctor.py::TestTwoProcessSmoke.
doctor-smoke:
	$(PY) tools/doctor_smoke.py

# Quantized-wire smoke: 2 CPU processes allreduce the same payload on the
# exact fp32 wire and the block-quantized int8 wire; every rank must hold
# byte-identical dequantized results, the quantized value must sit inside
# the int8 block error bound, and allreduce_wire_bytes_total must show a
# >= 3x wire-byte reduction. Also runs in tier-1 as
# tests/test_quantized_and_sharded.py::TestTwoProcessQuantSmoke.
quant-smoke:
	$(PY) tools/quant_smoke.py

# Preemption smoke: 2 CPU worker processes + 1 hot spare; rank 1 is
# SIGKILLed mid-epoch by HOROVOD_FAULT_PLAN, the launcher promotes the
# spare into the dead rank's slot, and the relaunched world restores from
# the last published sharded manifest. Exits nonzero unless recovery is
# within 2 steps of the kill, every resumed loss BIT-matches an
# uninterrupted golden run, and hvd.doctor() reports the measured
# recovery time as a ranked finding. Also runs in tier-1 as
# tests/test_checkpoint_sharded.py::TestTwoProcessPreemptSmoke.
preempt-smoke:
	$(PY) tools/preempt_smoke.py

# Network-transport serving smoke: 3 socket replicas (JSON-over-TCP,
# serving/transport.py), one SIGKILLed at its 8th RPC and one partitioned
# for 2s by HOROVOD_FAULT_PLAN; every request must reach a typed terminal
# state within its deadline (retries + circuit breakers + failover
# resubmission route around the faults), identical prompts must decode
# identically wherever they land, and hvd.doctor() must rank the breaker
# event. Also runs in tier-1 as tests/test_transport.py::TestNetSmoke.
net-smoke:
	$(PY) tools/net_smoke.py

# Topology smoke: 4 CPU processes simulate a 2x2 torus
# (HOROVOD_TOPOLOGY=2x2) and allreduce the same payload through
# rs_ag_2d / chunked_rs_ag_2d / swing / rs_ag_2d_int8; every rank must
# hold byte-identical results, each schedule must match psum, and the
# per-phase wire-byte legs must be observable. Also runs in tier-1 as
# tests/test_topology.py::TestFourProcessTopoSmoke.
topo-smoke:
	$(PY) tools/topo_smoke.py

# Self-healing fleet smoke: 3 socket replicas + 1 warm spare under a
# FleetSupervisor; HOROVOD_FAULT_PLAN SIGKILLs one replica twice
# (restart-with-backoff must bring it back), crash-loops another into a
# typed quarantine (the spare is promoted into its slot), and partitions
# a third for 2s (tolerated, no spurious restart). Then a rolling
# drain/restart of every live replica runs mid-load with zero dropped
# requests. All assertions come from the metrics snapshot, and
# hvd.doctor() must rank the quarantine. Also runs in tier-1 as
# tests/test_fleet.py::TestFleetSmoke.
fleet-smoke:
	$(PY) tools/fleet_smoke.py

# Shared-prefix + speculative-decode smoke: a high-overlap batch through
# two GPT-2 engines (prefix cache + spec lane on vs both off); asserts
# the shared preamble prefills once ever (index hit/reuse counters +
# per-request prefix_tokens), copy-on-write fires for a capped
# full-prefix match, token parity with offline greedy for all three
# families (T5 auto-disables sharing), a leak-free pool after drain, and
# spec acceptance > 0 with decode_compiles == 1. Also runs in tier-1 as
# tests/test_prefix.py::TestPrefixSmoke.
prefix-smoke:
	$(PY) tools/prefix_smoke.py

# dp×mp mesh smoke: 2 CPU processes on a dp=1×mp=2 named mesh
# (HOROVOD_MESH=dp1xmp2). ZeRO-3 GPT-2 training bit-exact in fp32 vs the
# 1-proc replicated baseline, tensor-parallel serving token-identical to
# offline generate() with decode_compiles == 1 (prefix cache + spec lane
# on) and per-rank param bytes <= 0.55x replicated. Also runs in tier-1
# as tests/test_mp.py::TestTwoProcessMpSmoke.
mp-smoke:
	$(PY) tools/mp_smoke.py

# Request-tracing smoke: 2 socket replicas + a hedging dispatcher, all
# writing request-trace shards (HOROVOD_REQUEST_TRACE=1). Replica 0 is
# rigged slow (busy single lane + a delay@...space=net on the traced
# submit) so the hedge fires and replica 1 wins; the merged trace must
# stitch one trace_id across all three processes, the requestReport
# breakdown must sum to the measured TTFT within 10%, and
# tools/tail_doctor.py must blame rank0's hedge wait. Also runs in
# tier-1 as tests/test_reqtrace.py::TestReqtraceSmoke.
reqtrace-smoke:
	$(PY) tools/reqtrace_smoke.py

# Config-bus smoke: 2 socket replicas under a FleetSupervisor with a
# shared auth token. apply_config(HEDGE_MS) must fan out fleet-wide
# with the driver and both replica audit ledgers agreeing on the epoch;
# a shape-affecting SERVE_SLOTS mutation is refused with a typed
# reason; an injected bad RPC_TIMEOUT mutation spikes retries, is
# measured `regressed`, auto-reverted (revert guard), and fires the
# doctor's config_regression alert — with decode_compiles==1 and token
# parity vs offline generate() held across all mutations. Also runs in
# tier-1 as tests/test_confbus.py::TestConfigSmoke.
config-smoke:
	$(PY) tools/config_smoke.py

# One frame of the fleet health dashboard (hvd.top): per-replica
# UP/QPS/TTFT_P99/SLOTS/BLOCKS/BREAKER from scraped /metrics.json
# windows, plus active alerts. Pass MEMBERS=/path/to/members.json to
# follow a live fleet's membership file; without it the local process
# registry is sampled. Drop --once (run the tool directly) for a live
# refreshing dashboard.
fleet-top:
	$(PY) tools/fleet_top.py --once $(if $(MEMBERS),--membership $(MEMBERS))

# Offline root-cause analysis of the newest flight-recorder bundle
# (HOROVOD_BLACKBOX): ranked findings from the crash-time events ring,
# the bundled metrics window (offline doctor), the pre-death alert tail
# and the queue trend. Pass BUNDLE=/path/to/postmortem-... to analyze a
# specific bundle, DIR=/path/to/blackbox to search elsewhere. Exit 2
# means a confident root cause was identified.
postmortem:
	$(PY) tools/postmortem.py $(BUNDLE) $(if $(DIR),--dir $(DIR))

# Regression sentinel over BENCH_SELF.jsonl: exit 2 when any proxy
# metric's newest line degrades >10% vs the latest prior line at equal
# settings (same model/metric/variant + settings fields). Comparison
# logic unit-tested in tests/test_bench_sentinel.py.
bench-sentinel:
	$(PY) tools/bench_sentinel.py

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'

native:
	$(MAKE) -C cpp
